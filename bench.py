#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Headline: GPT-124M (BASELINE.md config-4 class) training throughput on one
chip — jit-compiled full train step (fwd + loss + bwd + AdamW), bf16 AMP O2,
activation recompute, executed as ONE dispatch per WINDOW_STEPS-step window
(jit.WindowRunner: scanned steps, pre-staged inputs — per-step host work on
a network-attached chip otherwise dominates). vs_baseline = achieved MFU /
0.40, the A100-parity north star of BASELINE.md (the reference publishes no
absolute numbers, so parity-with-Paddle-CUDA is expressed as matching 40%
model-FLOPs utilization on the local chip's peak).

Budget discipline (round-3 rc:124 postmortem): everything expensive that
is NOT the headline — kernel-rate calibration, ResNet50/BERT north-star
secondaries — is persisted in benchmarks/measured/ keyed by device kind +
a content hash of the code that produced it, and only re-measured when
that code changes. The flash-attention block autotune cache is likewise
repo-persisted (PDTPU_CACHE_DIR below): a fresh environment re-tuning
from scratch costs ~7 minutes of compiles.

TPU rules (.claude/skills/verify/SKILL.md): everything through the jit
path; no SIGKILL; single process owns the chip.
"""
from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
# flash-attention autotune winners persist inside the repo (committed);
# ~/.cache is wiped between rounds and re-tuning costs minutes of compiles
os.environ.setdefault(
    "PDTPU_CACHE_DIR", os.path.join(_REPO, "benchmarks", "measured"))
sys.path.insert(0, os.path.join(_REPO, "benchmarks"))

import numpy as np

import measured_cache as mc

# bf16 peak FLOPs by device kind (per chip)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

WINDOW_STEPS = 200  # steps per dispatch; see extra.host_overhead
# (r5: the per-window launch cost is ~71 ms fixed — K=50 left
# 1.4 ms/step of it in the number; K=200 amortizes to 0.36 ms
# while the staged int32 ids stay a few MB)


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "")
    for k, v in _PEAK.items():
        if k.lower() in str(kind).lower():
            return v
    return 197e12  # assume v5e-class when unknown


# every _cached entry is timed through this shared harness — a change
# here must invalidate all cached rows, or a regression in the timing
# path would re-report stale numbers as current measured evidence.
# bench.py itself is hashed at FUNCTION granularity (the measurement
# fns, passed per entry) so cosmetic bench edits — emit format, extra
# wiring — cannot cold the whole cache and blow the driver's budget.
_HARNESS_FILES = [
    "paddle_tpu/jit/multi_step.py",
    "paddle_tpu/optimizer/optimizer.py",
    # the fused multi-tensor optimizer path runs inside every training
    # row's compiled step: its code must cold the training caches
    "paddle_tpu/optimizer/flat.py",
    "paddle_tpu/ops/pallas/fused_optimizer.py",
    # the fused flash-attention backward (ISSUE 11) is every training
    # row's dominant backward kernel: its code must cold the training
    # caches so the rebuilt backward re-measures on the next TPU run
    "paddle_tpu/ops/pallas/flash_attention.py",
    # the fused residual+norm glue kernels and the prefetch/remat train
    # loop (ISSUE 19) sit inside every training row's step: glue-kernel
    # or fit-loop code changes must cold the training caches so the
    # rows re-measure with the current chain on the next TPU run
    "paddle_tpu/ops/pallas/fused_residual_norm.py",
    "paddle_tpu/hapi/model.py",
    "paddle_tpu/amp/__init__.py",
    "paddle_tpu/nn/functional/norm.py",
    # distributed tracing + fleet aggregation (ISSUE 12) ride the
    # training rows' hot paths (compile spans in every capture,
    # dispatch/collective spans, gpt_3d's skew/compile_ms columns):
    # their code must re-measure the rows it can perturb
    "paddle_tpu/observability/tracing.py",
    "paddle_tpu/observability/aggregate.py",
    # SLO guardrails, stall watchdog and the regression sentinel
    # (ISSUE 14): the watchdog arms Model.fit's step loop, the SLO
    # engine judges the serving rows, and the sentinel's verdict rides
    # every round's JSON tail — their code must cold the caches so
    # rows re-measure under the current guardrails on the next TPU run
    "paddle_tpu/observability/slo.py",
    "paddle_tpu/observability/watchdog.py",
    "paddle_tpu/observability/regress.py",
    # elastic training recovery (ISSUE 15): the collective watchdog
    # arms Group.psum_mean / apply_collective_grads / the pipeline
    # dispatches in every training row, and hybrid_bench's recovery
    # column measures the supervisor itself — rows re-measure when the
    # recovery machinery changes
    "paddle_tpu/resilience/elastic_train.py",
]


def _fn_version(*fns):
    import hashlib
    import inspect
    h = hashlib.sha256()
    for f in fns:
        h.update(inspect.getsource(f).encode())
    return h.hexdigest()[:16]


def _cached(dev, name, files, fn, src_fns=()):
    """Measured-evidence gate: load from benchmarks/measured/ when the
    producing code is unchanged, else measure now and persist. The key
    covers the shared timing harness, the per-entry measurement fns,
    and the bench-module constants their math depends on."""
    import hashlib
    kind = str(getattr(dev, "device_kind", dev.platform))
    consts = repr((_PEAK, WINDOW_STEPS))
    ver = mc.code_version(*_HARNESS_FILES, *files) \
        + _fn_version(_timed_window, _peak_flops, *src_fns) \
        + hashlib.sha256(consts.encode()).hexdigest()[:8]
    val = mc.load(kind, name, ver)
    if val is not None:
        return dict(val, cached=True)
    val = fn()
    mc.store(kind, name, ver, val)
    return val


def _timed_window(step, example, batches, repeats=2):
    """Compile a WindowRunner over ``batches``, then return the best-of-
    ``repeats`` wall seconds for one window (inputs pre-staged; timed
    region = one scan launch + one scalar loss readback)."""
    import paddle_tpu as paddle

    w = paddle.jit.WindowRunner(step, example, length=len(batches))
    t0 = time.perf_counter()
    stacks = w.stage(batches)
    stage_s = time.perf_counter() - t0
    float(w.run(*stacks, outputs="last"))  # compile the scanned window
    dt, last = float("inf"), 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        last = float(w.run(*stacks, outputs="last"))
        dt = min(dt, time.perf_counter() - t0)
    return dt, stage_s, w, last


def _calibration(cfg, batch, seq):
    """Measured kernel rates at THIS model's GEMM/attention shapes via the
    dispatch-free scan-slope method (benchmarks/calibrate.py), plus the
    matmul+attention roofline they imply. The evidence behind the mfu
    number: achieved model-TF/s must sit below the roofline."""
    import calibrate as cal

    tokens = batch * seq
    h = cfg.hidden_size
    gemm_ffn, _ = cal.measure_matmul(tokens, h, 4 * h, r1=16, r2=96)
    gemm_lm, dt_lm = cal.measure_matmul(tokens, h, cfg.vocab_size,
                                        r1=4, r2=24)
    att = cal.measure_attention(batch, cfg.num_heads, seq,
                                h // cfg.num_heads, r1=8, r2=48)
    # per-kernel fwd/bwd breakdown (ISSUE 11): the attention bwd/fwd
    # ratio regression — acceptance <= 3x vs the 4.5x the two-pass
    # backward measured — plus the norm/fused-optimizer kernels, in
    # every calibration row
    kernels = cal.kernel_breakdown(batch, seq, h, cfg.num_heads,
                                   cfg.num_layers, att=att)
    return {
        "gemm_ffn_tflops": round(gemm_ffn, 1),
        "gemm_lmhead_tflops": round(gemm_lm, 1),
        "attention_fwd_tflops": att["fwd"]["tflops"],
        "attention_fwd_ms": att["fwd"]["ms"],
        "attention_bwd_ms": att["bwd"]["ms"],
        "attention_bwd_fwd_ratio": kernels["attention_bwd_fwd_ratio"],
        "kernels": kernels,
        "method": "scan-slope, dispatch-free (benchmarks/calibrate.py)",
    }


def _bench_resnet50(peak):
    """North star #1 (BASELINE.json): ResNet50 images/sec/chip, AMP O2."""
    import gc

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.vision.models import resnet50

    # batch 32 / window 48: the true device step is ~13.6 ms (K-slope,
    # r5) but the ~71 ms fixed per-window launch cost dominated the old
    # K=6 number (25.3 "ms/step" was ~12 ms/step of launch cost). The
    # staged fp32 inputs at K=48 are ~925 MB and fit alongside the
    # activation peak; batch 64 exceeds HBM
    batch, iters = 32, 48
    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    model, opt = amp.decorate(models=model, optimizers=opt, level="O2",
                              dtype="bfloat16", master_weight=True)

    @paddle.jit.to_static
    def step(x, y):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = paddle.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        x = rng.normal(size=(batch, 3, 224, 224)).astype(np.float32)
        y = rng.integers(0, 1000, (batch,)).astype(np.int64)
        return paddle.to_tensor(x), paddle.to_tensor(y)

    for _ in range(2):
        loss = step(*batch_fn())
    float(loss)
    dt, _stage, w, _ = _timed_window(step, batch_fn(),
                                     [batch_fn() for _ in range(iters)])
    img_s = batch * iters / dt
    # ResNet50 fwd = 4.089e9 MACs/img = 8.18e9 FLOPs (2 per MAC, the
    # same convention as the GPT/BERT 6N rows); train = fwd + ~2x bwd
    achieved = img_s * 3 * 2 * 4.089e9
    del w, step, model, opt
    gc.collect()
    # conv roofline (scan-slope, both layouts, representative shapes):
    # the measured ceiling evidence for why images/sec sits where it does
    # (convs are ~6 ms of the step at b32 — the rest is BN/elementwise
    # HBM traffic; NHWC ~= NCHW, XLA already lays out for the MXU)
    import calibrate as cal
    roof = cal.calibrate_resnet50(batch=batch, shapes=(
        "conv1_7x7_s2", "s1_3x3", "s2_3x3", "s3_3x3", "s4_3x3",
        "s3_expand_1x1"))
    return {"metric": "resnet50_train_images_per_sec_per_chip",
            "value": round(img_s, 1), "unit": "images/sec",
            "batch": batch,
            "step_time_ms": round(dt / iters * 1e3, 2),
            "amp": "O2-bf16-master",
            "model_tflops_per_sec": round(achieved / 1e12, 2),
            "mfu": round(achieved / peak, 4),
            "conv_roofline": roof["roofline"]}


def _bench_bert(peak):
    """North star #2: BERT-base pretraining tokens/sec/chip (MLM+NSP).

    max_predictions=76 (the standard max_predictions_per_seq for seq 512
    at 15% masking): the MLM head gathers the masked positions before
    the vocab projection, so the [*, 30522] GEMM runs over ~15% of
    positions. MFU counts the vocab-head FLOPs only for the positions
    actually projected (honest accounting — see flops_method)."""
    import gc

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    # iters 32 (was 8): amortizes the ~71 ms fixed window-launch
    # cost to ~2 ms/step (see the r5 K-slope finding)
    batch, seq, iters, maxpred = 16, 512, 32, 76
    cfg = BertConfig(recompute=True,
                     recompute_policy="dots_and_kernels_saveable",
                     max_predictions=maxpred)
    paddle.seed(0)
    model = BertForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    model, opt = amp.decorate(models=model, optimizers=opt, level="O2",
                              dtype="bfloat16", master_weight=True)

    @paddle.jit.to_static
    def step(ids, seg, mlm, nsp):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = model(ids, seg, mlm, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        seg = np.zeros((batch, seq), np.int32)
        # <= maxpred masked positions per row (the reference pipeline's
        # max_predictions_per_seq contract)
        mlm = np.full((batch, seq), -100, np.int32)
        for b in range(batch):
            pos = rng.choice(seq, size=maxpred, replace=False)
            mlm[b, pos] = rng.integers(0, cfg.vocab_size, maxpred)
        nsp = rng.integers(0, 2, (batch,)).astype(np.int64)
        return tuple(paddle.to_tensor(v) for v in (ids, seg, mlm, nsp))

    for _ in range(2):
        loss = step(*batch_fn())
    float(loss)
    dt, _stage, w, _ = _timed_window(step, batch_fn(),
                                     [batch_fn() for _ in range(iters)])
    tok_s = batch * seq * iters / dt
    n = model.num_params()
    h, v = cfg.hidden_size, cfg.vocab_size
    # per-token model flops: 6*(N - vocab head) everywhere + the vocab
    # head only on the maxpred/seq fraction actually projected
    head = v * h
    flops_tok = (6.0 * (n - head) + 6.0 * head * (maxpred / seq)
                 + 12 * cfg.num_layers * h * seq)
    achieved = tok_s * flops_tok
    del w, step, model, opt
    gc.collect()
    return {"metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": round(tok_s, 1), "unit": "tokens/sec",
            "batch": batch, "seq_len": seq,
            "max_predictions": maxpred,
            "step_time_ms": round(dt / iters * 1e3, 2),
            "params": n, "amp": "O2-bf16-master",
            "model_tflops_per_sec": round(achieved / 1e12, 2),
            "mfu": round(achieved / peak, 4),
            "flops_method": ("6*(N - vocab_head) + 6*vocab_head*"
                             "(max_predictions/seq) + 12*L*H*S per token; "
                             "vocab-head flops counted only for projected "
                             "positions")}


def _bench_gpt_3d(peak):
    """Training-secondary row: hybrid DP x TP x PP GPT step over the
    fleet topology (benchmarks/hybrid_bench.py — tokens/sec on the full
    mesh, weak-scaling ratio vs 1 device, and the overlap scheduler's
    comm_ms / overlap_frac). Raises below 4 devices (single-chip rounds
    simply skip the row; the multichip driver picks it up)."""
    import jax

    import hybrid_bench
    if len(jax.devices()) < 4:
        raise RuntimeError("gpt_3d needs >= 4 devices")
    return hybrid_bench.bench_row(peak_flops=peak)


def _bench_optimizer():
    """Training-secondary row: fused vs per-param optimizer update at
    BERT-base and ResNet50 param sets (benchmarks/optimizer_bench.py —
    HLO update-op counts + eager update time + dispatch counts)."""
    import optimizer_bench
    return optimizer_bench.bench_row(small=False)


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    # whole-program audit bookkeeping (ISSUE 16): zero the per-code
    # finding counters so this round's record reports only programs
    # compiled by this bench process
    from paddle_tpu import analysis as _analysis
    _analysis.audit_counts(reset=True)

    if on_tpu:
        # dots_and_kernels_saveable: remat keeps matmul AND Pallas
        # (flash-attention) outputs, recomputing only elementwise ops —
        # measured 99.9 vs 104.2 ms/step over dots_saveable (the flash fwd
        # re-run in backward costs ~4 ms/step). batch 16 and recompute=False
        # both exceed HBM; XLA attention OOMs on the saved s^2 probs, so the
        # Pallas flash path is also the memory enabler
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024, dropout=0.0,
                        recompute=True,
                        recompute_policy="dots_and_kernels_saveable")
        batch, seq, warmup, iters = 8, 1024, 2, WINDOW_STEPS
    else:  # CPU smoke (local testing only; driver runs on the real chip)
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0,
                        recompute=True)
        batch, seq, warmup, iters = 2, 64, 2, 4

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    if on_tpu:
        # O2 (bf16 params + fp32 master weights) measured ~3% over O1:
        # per-op input casts disappear from the compiled step
        model, opt = amp.decorate(models=model, optimizers=opt,
                                  level="O2", dtype="bfloat16",
                                  master_weight=True)

    if on_tpu:
        # flash-attention block sizes for this model's shapes come from
        # the repo-persisted autotune cache (benchmarks/measured/); on a
        # cache miss this probe re-measures once (slope-timed,
        # validated) and persists the winner. The grad probe warms the
        # SEPARATE flash_attention_bwd entry (the fused backward tunes
        # its own blocks) so the train step never sweeps mid-window.
        import jax.numpy as jnp

        from paddle_tpu.incubate import autotune
        from paddle_tpu.ops.pallas import flash_attention as fa
        autotune.set_config({"kernel": {"enable": True}})
        probe = jnp.zeros((batch, seq, cfg.num_heads, cfg.head_dim),
                          jnp.bfloat16)
        import jax as _jax
        _jax.grad(lambda q, k, v: fa.flash_attention(
            q, k, v, causal=True).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(probe, probe, probe)

    level = "O2" if on_tpu else "O1"

    @paddle.jit.to_static
    def train_step(ids, labels):
        with amp.auto_cast(level=level, dtype="bfloat16"):
            loss = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        lab = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    for _ in range(warmup):
        loss = train_step(*batch_fn())
    float(loss)  # sync

    # ONE dispatch per window of `iters` scanned steps, inputs pre-staged
    # on device (jit.WindowRunner): per-step host work — stack/slice
    # dispatches and the first-step launch — is hoisted out of the loop.
    # best of 3 windows: the axon tunnel adds +-10% run-to-run scheduling
    # noise on top of stable device time (profiled)
    dt, stage_s, w, final_loss = _timed_window(
        train_step, batch_fn(), [batch_fn() for _ in range(iters)],
        repeats=3)
    stage_ms = stage_s * 1e3

    tokens_per_sec = batch * seq * iters / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    peak = _peak_flops(dev)
    mfu = achieved / peak if on_tpu else 0.0

    extra = {
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "batch": batch, "seq_len": seq, "iters": iters,
        "step_time_ms": round(dt / iters * 1e3, 2),
        "params": model.num_params(),
        "model_tflops_per_sec": round(achieved / 1e12, 2),
        "mfu": round(mfu, 4),
        "final_loss": round(final_loss, 4),
        "amp": "O2-bf16-master" if on_tpu else "O1-bf16", "recompute": True,
        "dispatch": "WindowRunner (1 dispatch / %d steps, inputs "
                    "pre-staged on device)" % iters,
        "host_overhead": {
            "stage_upload_ms_per_window": round(stage_ms, 1),
            "note": ("input staging happens once per window outside the "
                     "step loop; the timed region is one scan launch + "
                     "one scalar loss readback")},
        "flops_method": ("6*N_params + 12*L*H*S per token; backward "
                         "counted once, remat recompute NOT counted "
                         "(true-work MFU)"),
    }

    # static-vs-measured HBM accounting (ISSUE 16): the whole-program
    # audit's live-range sweep predicted a peak at compile time; compare
    # it against the measured captured-state residency while train_step
    # is still alive. ratio is the acceptance check (static within 25%
    # of measured program_state_bytes).
    try:
        from paddle_tpu import jit as _jit_mod
        static_b = _jit_mod._static_peak_bytes("train_step")
        measured_b = _jit_mod._program_state_bytes("train_step")
        if static_b and measured_b:
            extra["analysis_hbm"] = {
                "static_peak_bytes": int(static_b),
                "program_state_bytes": int(measured_b),
                "static_over_measured": round(static_b / measured_b, 3),
            }
    except Exception as e:  # accounting must never kill the bench
        print(f"analysis hbm accounting failed: {e}", file=sys.stderr)

    headline = {
        "metric": "gpt124m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }

    def emit_enriched():
        print(json.dumps(dict(headline, extra=extra)), flush=True)

    def emit_compact():
        """The LAST stdout line, kept well under 500 bytes: the driver
        stores only the final 2000 BYTES of stdout and parses the last
        line, so the ~2.4KB enriched record must never be last (round-4
        postmortem: rc:0 but parsed:null — the line arrived beheaded).
        The enriched evidence is printed above AND persisted to
        benchmarks/measured/headline.json."""
        brief = {"device": extra["device"],
                 "step_time_ms": extra["step_time_ms"],
                 "mfu": extra["mfu"]}
        # the sentinel's verdict belongs in the tail the driver parses
        # (empty list = judged clean; absent = sentinel didn't run)
        if "regressions" in extra:
            brief["regressions"] = extra["regressions"][:4]
        for key, short in (("resnet50_train_images_per_sec_per_chip",
                            "resnet50"),
                           ("bert_base_pretrain_tokens_per_sec_per_chip",
                            "bert")):
            row = extra.get("secondary", {}).get(key)
            if row:
                brief[short] = {"value": row["value"], "unit": row["unit"],
                                "mfu": row["mfu"]}
        line = json.dumps(dict(headline, extra=brief))
        # never let the guard recreate the failure it prevents: drop
        # optional entries (newest first) until the line fits
        while len(line) > 500 and brief:
            brief.pop(next(reversed(brief)))
            line = json.dumps(dict(headline, extra=brief))
        if len(line) > 500:
            line = json.dumps(headline)
        print(line, flush=True)

    # kill-safety: the headline is measured — emit it NOW (compact, so
    # it parses even if the process dies mid-extras). The enriched
    # record below attaches calibration + north-star secondaries (cache
    # hits in benchmarks/measured/ unless their producing code changed),
    # then a compact line is re-emitted LAST.
    if on_tpu:
        emit_compact()
        import gc
        try:
            extra["calibration"] = _cached(
                dev, "calibration_gpt124m_b8s1024",
                ["benchmarks/calibrate.py",
                 "paddle_tpu/ops/pallas/flash_attention.py"],
                lambda: _calibration(cfg, batch, seq),
                src_fns=(_calibration,))
        except Exception as e:
            print(f"calibration failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
        # free the GPT params/moments/compiled programs BEFORE the
        # secondary models — leaving them resident OOMs ResNet50/BERT
        del w, train_step, model, opt
        gc.collect()
        for name, files, fn, src in (
            ("secondary_resnet50",
             ["benchmarks/calibrate.py",
              "paddle_tpu/vision/models/resnet.py",
              "paddle_tpu/nn/functional/conv.py"],
             lambda: _bench_resnet50(peak), (_bench_resnet50,)),
            ("secondary_bert",
             ["paddle_tpu/models/bert.py",
              "paddle_tpu/ops/pallas/flash_attention.py",
              "paddle_tpu/distributed/fleet/recompute.py"],
             lambda: _bench_bert(peak), (_bench_bert,)),
            ("secondary_optimizer",
             ["benchmarks/optimizer_bench.py"],
             _bench_optimizer, (_bench_optimizer,)),
            ("secondary_gpt_3d",
             ["benchmarks/hybrid_bench.py",
              "paddle_tpu/distributed/fleet/pipeline.py",
              "paddle_tpu/distributed/fleet/topology.py",
              "paddle_tpu/distributed/overlap.py",
              "paddle_tpu/distributed/parallel.py",
              "paddle_tpu/core/meshutil.py"],
             lambda: _bench_gpt_3d(peak), (_bench_gpt_3d,)),
        ):
            try:
                row = _cached(dev, name, files, fn, src_fns=src)
                extra.setdefault("secondary", {})[row["metric"]] = {
                    k: v for k, v in row.items() if k != "metric"}
            except Exception as e:  # secondary must never kill the bench
                print(f"secondary bench failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
            gc.collect()
        try:
            # serving rows are measured separately (benchmarks/
            # serving_bench.py, run on the chip outside the bench's
            # time budget) and embedded from the cache here
            import serving_bench
            srows = serving_bench.cached_rows(dev)
            if srows:
                extra["serving"] = {
                    k: {"ms_per_token": v["ms_per_token"],
                        "tokens_per_sec": v["tokens_per_sec"],
                        "kv_cache": v["kv_cache"], "batch": v["batch"]}
                    for k, v in srows.items() if "ms_per_token" in v}
                if "analysis" in srows:
                    extra.setdefault("analysis", {})[
                        "serving_findings"] = srows["analysis"]["findings"]
        except Exception as e:
            print(f"serving rows unavailable: {e}", file=sys.stderr)

    # per-code whole-program audit finding counts (ISSUE 16): the
    # sentinel judges them lower-is-better (regress.py special-cases
    # PDT* leaves), so a new warn-class finding in a compiled program
    # shows up as a regression against the checked-in history
    try:
        extra.setdefault("analysis", {})[
            "findings"] = _analysis.audit_counts()
    except Exception as e:
        print(f"audit counts unavailable: {e}", file=sys.stderr)

    # regression sentinel (ISSUE 14): judge THIS round against the
    # checked-in BENCH_r* history (median/MAD baselines; see
    # paddle_tpu/observability/regress.py) so the record self-reports
    # its own regressions in the JSON tail — the driver and the next
    # session see the dip without diffing history by hand.  TPU rounds
    # only: the history is TPU-measured, so judging a CPU smoke
    # against it would flag the hardware, not the code.
    if on_tpu:
        try:
            from paddle_tpu.observability import regress as _regress
            regs = _regress.check_record(dict(headline, extra=extra),
                                         _REPO)
            extra["regressions"] = regs
            if regs:
                print("regression sentinel: " + ", ".join(regs),
                      file=sys.stderr)
        except Exception as e:
            print(f"regression sentinel failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)

    # full evidence: to stdout (NOT last) and to a persisted file that
    # survives regardless of how the driver captures stdout
    emit_enriched()
    try:
        with open(os.path.join(_REPO, "benchmarks", "measured",
                               "headline.json"), "w") as f:
            json.dump(dict(headline, extra=extra), f, indent=1)
    except OSError as e:
        print(f"headline persist failed: {e}", file=sys.stderr)
    emit_compact()


if __name__ == "__main__":
    try:
        try:
            main()
        except Exception as e:
            # the axon remote-compile tunnel drops long requests
            # transiently ("response body closed before all bytes were
            # read", observed twice in r5); one retry usually clears it
            if "remote_compile" not in str(e):
                raise
            print(f"transient compile-tunnel failure, retrying: {e}",
                  file=sys.stderr)
            main()
    except Exception as e:  # still emit a parseable line on failure
        print(json.dumps({
            "metric": "gpt124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"}))
        sys.exit(1)

#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Headline: GPT-124M (BASELINE.md config-4 class) training throughput on one
chip — jit-compiled full train step (fwd + loss + bwd + AdamW), bf16 AMP O1,
activation recompute. vs_baseline = achieved MFU / 0.40, the A100-parity
north star of BASELINE.md (the reference publishes no absolute numbers, so
parity-with-Paddle-CUDA is expressed as matching 40% model-FLOPs
utilization on the local chip's peak).

TPU rules (.claude/skills/verify/SKILL.md): everything through the jit
path; no SIGKILL; single process owns the chip.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# bf16 peak FLOPs by device kind (per chip)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "")
    for k, v in _PEAK.items():
        if k.lower() in str(kind).lower():
            return v
    return 197e12  # assume v5e-class when unknown


def _calibration(cfg, batch, seq):
    """Measured kernel rates at THIS model's GEMM/attention shapes via the
    dispatch-free scan-slope method (benchmarks/calibrate.py), plus the
    matmul+attention roofline they imply. The evidence behind the mfu
    number: achieved model-TF/s must sit below the roofline."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmarks"))
    import calibrate as cal

    tokens = batch * seq
    h = cfg.hidden_size
    gemm_ffn, _ = cal.measure_matmul(tokens, h, 4 * h, r1=16, r2=96)
    gemm_lm, dt_lm = cal.measure_matmul(tokens, h, cfg.vocab_size,
                                        r1=4, r2=24)
    att = cal.measure_attention(batch, cfg.num_heads, seq,
                                h // cfg.num_heads, r1=8, r2=48)
    return {
        "gemm_ffn_tflops": round(gemm_ffn, 1),
        "gemm_lmhead_tflops": round(gemm_lm, 1),
        "attention_fwd_tflops": att["fwd"]["tflops"],
        "attention_fwd_ms": att["fwd"]["ms"],
        "attention_bwd_ms": att["bwd"]["ms"],
        "method": "scan-slope, dispatch-free (benchmarks/calibrate.py)",
    }


def _window_time(train_step, batches, repeats=2, with_loss=False):
    """Best-of-N timed multi_step windows (compile via a first throwaway
    window); returns seconds per window (and the last loss if asked)."""
    import time as _time

    from paddle_tpu.jit import multi_step

    losses = multi_step(train_step, batches)
    last = float(losses[-1])  # compile + sync
    best = float("inf")
    for _ in range(repeats):
        t0 = _time.perf_counter()
        losses = multi_step(train_step, batches)
        last = float(losses[-1])
        best = min(best, _time.perf_counter() - t0)
    return (best, last) if with_loss else best


def _bench_resnet50(peak):
    """North star #1 (BASELINE.json): ResNet50 images/sec/chip, AMP O2."""
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.vision.models import resnet50

    batch, iters = 32, 6
    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    model, opt = amp.decorate(models=model, optimizers=opt, level="O2",
                              dtype="bfloat16", master_weight=True)

    @paddle.jit.to_static
    def step(x, y):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = paddle.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        x = rng.normal(size=(batch, 3, 224, 224)).astype(np.float32)
        y = rng.integers(0, 1000, (batch,)).astype(np.int64)
        return paddle.to_tensor(x), paddle.to_tensor(y)

    for _ in range(2):
        loss = step(*batch_fn())
    float(loss)
    dt = _window_time(step, [batch_fn() for _ in range(iters)])
    img_s = batch * iters / dt
    # ResNet50 fwd = 4.089e9 MACs/img = 8.18e9 FLOPs (2 per MAC, the
    # same convention as the GPT/BERT 6N rows); train = fwd + ~2x bwd
    achieved = img_s * 3 * 2 * 4.089e9
    return {"metric": "resnet50_train_images_per_sec_per_chip",
            "value": round(img_s, 1), "unit": "images/sec",
            "extra": {"batch": batch,
                      "step_time_ms": round(dt / iters * 1e3, 2),
                      "amp": "O2-bf16-master",
                      "model_tflops_per_sec": round(achieved / 1e12, 2),
                      "mfu": round(achieved / peak, 4)}}


def _bench_bert(peak):
    """North star #2: BERT-base pretraining tokens/sec/chip (MLM+NSP)."""
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    batch, seq, iters = 16, 512, 6
    cfg = BertConfig(recompute=True, recompute_policy="dots_saveable")
    paddle.seed(0)
    model = BertForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    model, opt = amp.decorate(models=model, optimizers=opt, level="O2",
                              dtype="bfloat16", master_weight=True)

    @paddle.jit.to_static
    def step(ids, seg, mlm, nsp):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = model(ids, seg, mlm, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        seg = np.zeros((batch, seq), np.int32)
        mlm = np.where(rng.uniform(size=(batch, seq)) < 0.15,
                       rng.integers(0, cfg.vocab_size, (batch, seq)),
                       -100).astype(np.int32)
        nsp = rng.integers(0, 2, (batch,)).astype(np.int64)
        return tuple(paddle.to_tensor(v) for v in (ids, seg, mlm, nsp))

    for _ in range(2):
        loss = step(*batch_fn())
    float(loss)
    dt = _window_time(step, [batch_fn() for _ in range(iters)])
    tok_s = batch * seq * iters / dt
    n = model.num_params()
    achieved = tok_s * (6.0 * n + 12 * cfg.num_layers
                        * cfg.hidden_size * seq)
    return {"metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": round(tok_s, 1), "unit": "tokens/sec",
            "extra": {"batch": batch, "seq_len": seq,
                      "step_time_ms": round(dt / iters * 1e3, 2),
                      "params": n, "amp": "O2-bf16-master",
                      "model_tflops_per_sec": round(achieved / 1e12, 2),
                      "mfu": round(achieved / peak, 4)}}


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # dots_saveable: remat recomputes elementwise only, keeping matmul
        # outputs — measured +2% over full remat at this size (batch 16 and
        # recompute=False both exceed HBM; XLA attention OOMs on the saved
        # s^2 probs, so the Pallas flash path is also the memory enabler)
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024, dropout=0.0,
                        recompute=True, recompute_policy="dots_saveable")
        batch, seq, warmup, iters = 8, 1024, 2, 10
    else:  # CPU smoke (local testing only; driver runs on the real chip)
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0,
                        recompute=True)
        batch, seq, warmup, iters = 2, 64, 2, 4

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    if on_tpu:
        # O2 (bf16 params + fp32 master weights) measured ~3% over O1:
        # per-op input casts disappear from the compiled step
        model, opt = amp.decorate(models=model, optimizers=opt,
                                  level="O2", dtype="bfloat16",
                                  master_weight=True)

    if on_tpu:
        # tune the flash-attention block sizes for this model's shapes
        # (measured once per device+shape, persisted; the captured train
        # step then picks the winner from the cache at trace time)
        import jax.numpy as jnp

        from paddle_tpu.incubate import autotune
        from paddle_tpu.ops.pallas import flash_attention as fa
        autotune.set_config({"kernel": {"enable": True}})
        probe = jnp.zeros((batch, seq, cfg.num_heads, cfg.head_dim),
                          jnp.bfloat16)
        fa.flash_attention(probe, probe, probe, causal=True)

    level = "O2" if on_tpu else "O1"

    @paddle.jit.to_static
    def train_step(ids, labels):
        with amp.auto_cast(level=level, dtype="bfloat16"):
            loss = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        lab = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        return paddle.to_tensor(ids), paddle.to_tensor(lab)

    for _ in range(warmup):
        loss = train_step(*batch_fn())
    float(loss)  # sync

    # timed window: ONE dispatch for all iters via the scanned multi-step
    # program — per-step host dispatch (~13 ms/step over the axon tunnel,
    # profiled) would otherwise be billed to the chip
    # best of 3 windows: the axon tunnel adds +-10% run-to-run scheduling
    # noise (device busy time is stable — profiled); best-of reports the
    # chip's actual capability
    dt, final_loss = _window_time(
        train_step, [batch_fn() for _ in range(iters)], repeats=3,
        with_loss=True)

    tokens_per_sec = batch * seq * iters / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    peak = _peak_flops(dev)
    mfu = achieved / peak if on_tpu else 0.0

    extra = {
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "batch": batch, "seq_len": seq, "iters": iters,
        "step_time_ms": round(dt / iters * 1e3, 2),
        "params": model.num_params(),
        "model_tflops_per_sec": round(achieved / 1e12, 2),
        "mfu": round(mfu, 4),
        "final_loss": round(final_loss, 4),
        "amp": "O2-bf16-master" if on_tpu else "O1-bf16", "recompute": True,
        "dispatch": "multi_step window (1 dispatch / %d steps)" % iters,
        "flops_method": ("6*N_params + 12*L*H*S per token; backward "
                         "counted once, remat recompute NOT counted "
                         "(true-work MFU)"),
    }
    def emit():
        print(json.dumps({
            "metric": "gpt124m_train_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 1),
            "unit": "tokens/sec",
            "vs_baseline": round(mfu / 0.40, 4),
            "extra": extra,
        }), flush=True)

    # kill-safety: the headline is measured — emit it NOW. The enriched
    # line (calibration + north-star secondaries, ~20 extra minutes of
    # compiles) re-emits the same metric afterwards; line-scanning
    # parsers get a valid record whether they take the first or the
    # last line, even if the process is killed mid-extras.
    if on_tpu:
        emit()
        extra["calibration"] = _calibration(cfg, batch, seq)
        # free the GPT params/moments/compiled programs BEFORE the
        # secondary models — leaving them resident OOMs ResNet50/BERT
        import gc
        del train_step, model, opt
        gc.collect()
        import sys as _sys
        for fn in (_bench_resnet50, _bench_bert):
            try:
                row = fn(peak)
                extra.setdefault("secondary", {})[row["metric"]] = {
                    "value": row["value"], "unit": row["unit"],
                    **row["extra"]}
            except Exception as e:  # secondary must never kill the bench
                print(f"secondary bench failed: {type(e).__name__}: {e}",
                      file=_sys.stderr)
            gc.collect()

    emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # still emit a parseable line on failure
        print(json.dumps({
            "metric": "gpt124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"}))
        sys.exit(1)

"""Op-surface completion batch (reference ``python/paddle/tensor/``:
manipulation.py, math.py, search.py entries absent from the first op
sweep — multiplex, crop, fill_diagonal*, renorm, dist, diff, stack
variants, atleast_*, block_diag, signbit family, ldexp/frexp, bucketize,
take, vander, trapezoid, combinations, edit_distance)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor


@primitive
def multiplex(inputs, index):
    """out[i] = inputs[index[i]][i] (reference ``multiplex``)."""
    stacked = jnp.stack(inputs, axis=0)            # [K, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)      # [N]
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


@primitive
def crop(x, shape=None, offsets=None):
    """Reference ``crop``: slice ``shape`` starting at ``offsets``."""
    shp = [int(s) for s in (unwrap(shape) if shape is not None
                            else x.shape)]
    shp = [x.shape[i] if s in (-1, None) else s for i, s in enumerate(shp)]
    off = [int(o) for o in (unwrap(offsets) if offsets is not None
                            else [0] * x.ndim)]
    sl = tuple(builtins.slice(o, o + s) for o, s in zip(off, shp))
    return x[sl]


def _diag_indices(n, m, offset):
    """Static diagonal coordinates of an [.., n, m] matrix at ``offset``."""
    k = builtins.min(n, m - offset) if offset >= 0 else \
        builtins.min(n + offset, m)
    k = builtins.max(k, 0)
    i = np.arange(k)
    return i - builtins.min(offset, 0), i + builtins.max(offset, 0)


@primitive
def fill_diagonal(x, value, offset=0, wrap=False):
    """Reference ``fill_diagonal_`` (out-of-place on this backend). With
    ``wrap`` the diagonal restarts every ``m+1`` rows of a tall 2-D
    matrix."""
    n, m = x.shape[-2], x.shape[-1]
    if wrap and x.ndim == 2 and n > m:
        rs, cs = [], []
        for block in range(0, n, m + 1):
            r, c = _diag_indices(builtins.min(m, n - block), m, offset)
            rs.append(r + block)
            cs.append(c)
        rows, cols = np.concatenate(rs), np.concatenate(cs)
    else:
        rows, cols = _diag_indices(n, m, offset)
    if len(rows) == 0:
        return x
    return x.at[..., rows, cols].set(jnp.asarray(value, x.dtype))


@primitive
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    """Reference ``fill_diagonal_tensor``: write tensor ``y`` (its last
    dim running along the diagonal) onto the (dim1, dim2) diagonal."""
    d1, d2 = dim1 % x.ndim, dim2 % x.ndim
    perm = [d for d in range(x.ndim) if d not in (d1, d2)] + [d1, d2]
    inv = np.argsort(perm)
    xt = jnp.transpose(x, perm)
    rows, cols = _diag_indices(xt.shape[-2], xt.shape[-1], offset)
    if len(rows) == 0:
        return x
    out = xt.at[..., rows, cols].set(jnp.asarray(y, x.dtype))
    return jnp.transpose(out, inv)


@primitive
def renorm(x, p, axis, max_norm):
    """Reference ``renorm``: scale slices along ``axis`` whose p-norm
    exceeds ``max_norm`` down to it."""
    axes = tuple(d for d in range(x.ndim) if d != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


@primitive
def dist(x, y, p=2.0):
    """Reference ``dist``: p-norm of (x - y) after broadcast."""
    d = (x - y).reshape(-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@primitive
def diff(x, n=1, axis=-1, prepend=None, append=None):
    parts = [v for v in (prepend, x, append) if v is not None]
    v = jnp.concatenate(parts, axis=axis) if len(parts) > 1 else x
    return jnp.diff(v, n=n, axis=axis)


@primitive
def unflatten(x, axis, shape):
    shp = list(x.shape)
    axis %= x.ndim
    return x.reshape(tuple(shp[:axis]) + tuple(int(s) for s in shape)
                     + tuple(shp[axis + 1:]))


@primitive
def index_fill(x, index, axis, value):
    idx = index.astype(jnp.int32)
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[idx].set(jnp.asarray(value, x.dtype))
    return jnp.moveaxis(moved, 0, axis)


def _stackish(jfn, name):
    @primitive(name)
    def op(inputs):
        return jfn([jnp.asarray(v) for v in inputs])
    return lambda x, name=None: op(list(x))


hstack = _stackish(jnp.hstack, "hstack")
vstack = _stackish(jnp.vstack, "vstack")
dstack = _stackish(jnp.dstack, "dstack")
column_stack = _stackish(jnp.column_stack, "column_stack")
row_stack = _stackish(jnp.vstack, "row_stack")


def atleast_1d(*xs):
    from ..core.dispatch import apply
    outs = [apply("atleast_1d", jnp.atleast_1d, x) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs):
    from ..core.dispatch import apply
    outs = [apply("atleast_2d", jnp.atleast_2d, x) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs):
    from ..core.dispatch import apply
    outs = [apply("atleast_3d", jnp.atleast_3d, x) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def block_diag(inputs, name=None):
    from ..core.dispatch import apply
    return apply("block_diag",
                 lambda *vs: jax.scipy.linalg.block_diag(*vs), *inputs)


@primitive
def signbit(x):
    return jnp.signbit(x)


@primitive
def isneginf(x):
    return jnp.isneginf(x)


@primitive
def isposinf(x):
    return jnp.isposinf(x)


@primitive
def isreal(x):
    return jnp.isreal(x)


@primitive
def ldexp(x, y):
    return jnp.ldexp(x, y.astype(jnp.int32))


@primitive
def frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


@primitive
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@primitive
def take(x, index, mode="raise"):
    """Reference ``take``: flat-index gather with clip/wrap modes."""
    flat = x.reshape(-1)
    idx = index.astype(jnp.int32)
    n = flat.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    else:  # raise-mode bounds checks need host sync; clip matches docs
        idx = jnp.clip(jnp.where(idx < 0, idx + n, idx), 0, n - 1)
    return flat[idx]


@primitive
def slice_scatter(x, value, axes, starts, ends, strides):
    sl = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        sl[ax] = builtins.slice(int(st), int(en), int(sd))
    return x.at[tuple(sl)].set(value.astype(x.dtype))


@primitive
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


@primitive
def trapezoid(y, x=None, dx=None, axis=-1):
    if x is not None:
        return jax.scipy.integrate.trapezoid(y, x=x, axis=axis)
    return jax.scipy.integrate.trapezoid(
        y, dx=1.0 if dx is None else dx, axis=axis)


@primitive
def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    axis %= y.ndim
    yl = jnp.moveaxis(y, axis, -1)
    if x is not None:
        xv = (jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1)
              if x.ndim == y.ndim else x)
        d = jnp.diff(xv, axis=-1)
    else:
        d = 1.0 if dx is None else dx
    avg = (yl[..., 1:] + yl[..., :-1]) / 2.0
    out = jnp.cumsum(avg * d, axis=-1)
    return jnp.moveaxis(out, -1, axis)


def combinations(x, r=2, with_replacement=False, name=None):
    """Reference ``combinations``: static index enumeration + gather."""
    import itertools

    from ..core.dispatch import apply
    n = int(x.shape[0])
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), np.int32).reshape(-1, r)
    return apply("combinations", lambda v: v[jnp.asarray(idx)], x)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Reference ``nn/functional/loss.py edit_distance`` (Levenshtein,
    batch of sequences). Host-side DP (the reference's CPU kernel is the
    same loop); returns (distances [B,1], sequence_num)."""
    a = np.asarray(unwrap(input))
    b = np.asarray(unwrap(label))
    ilen = (np.asarray(unwrap(input_length)) if input_length is not None
            else np.full(a.shape[0], a.shape[1]))
    llen = (np.asarray(unwrap(label_length)) if label_length is not None
            else np.full(b.shape[0], b.shape[1]))
    ign = set(ignored_tokens or [])
    out = np.zeros((a.shape[0], 1), np.float32)
    for r in range(a.shape[0]):
        s1 = [t for t in a[r][: int(ilen[r])] if t not in ign]
        s2 = [t for t in b[r][: int(llen[r])] if t not in ign]
        dp = np.arange(len(s2) + 1, dtype=np.float32)
        for i, c1 in enumerate(s1, 1):
            prev, dp[0] = dp[0], i
            for j, c2 in enumerate(s2, 1):
                cur = dp[j]
                dp[j] = builtins.min(dp[j] + 1, dp[j - 1] + 1,
                                     prev + (c1 != c2))
                prev = cur
        d = dp[-1]
        if normalized:
            d = d / builtins.max(len(s2), 1)
        out[r, 0] = d
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray([a.shape[0]]))


__all__ = [
    "multiplex", "crop", "fill_diagonal", "fill_diagonal_tensor",
    "renorm", "dist", "diff", "unflatten", "index_fill", "hstack",
    "vstack", "dstack", "column_stack", "row_stack", "atleast_1d",
    "atleast_2d", "atleast_3d", "block_diag", "signbit", "isneginf",
    "isposinf", "isreal", "ldexp", "frexp", "bucketize", "take",
    "slice_scatter", "vander", "trapezoid", "cumulative_trapezoid",
    "combinations", "edit_distance",
]

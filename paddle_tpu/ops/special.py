"""Special functions + op-surface completion batch 2 (reference
``ops.yaml`` rows absent from the first sweeps: gammaln/gammaincc/
polygamma, nanmedian, standard_gamma/binomial sampling, add_n, eigvals,
lu_unpack, clip_by_norm, gather_tree, viterbi_decode, top_p_sampling)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jsp

from ..core import state
from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor


@primitive
def gammaln(x):
    """Reference ``gammaln``: log |Gamma(x)|."""
    return jsp.gammaln(x)


@primitive
def gammainc(x, y):
    """Reference ``gammainc``: lower regularized incomplete gamma P(x, y)."""
    return jsp.gammainc(x, y)


@primitive
def gammaincc(x, y):
    """Reference ``gammaincc``: upper regularized incomplete gamma Q(x, y)."""
    return jsp.gammaincc(x, y)


@primitive
def polygamma(x, n=1):
    """Reference ``polygamma``: n-th derivative of digamma."""
    return jsp.polygamma(n, x)


@primitive
def nanmedian(x, axis=None, keepdim=False):
    """Reference ``nanmedian``: median ignoring NaNs."""
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def add_n(inputs, name=None):
    """Reference ``add_n``: elementwise sum of a tensor list."""
    if isinstance(inputs, Tensor):
        return inputs

    @primitive(name="add_n")
    def _add_n(xs):
        out = xs[0]
        for v in xs[1:]:
            out = out + v
        return out

    return _add_n(list(inputs))


@primitive
def clip_by_norm(x, max_norm):
    """Reference ``clip_by_norm``: scale x so its L2 norm <= max_norm."""
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return (x * scale.astype(x.dtype))


def standard_gamma(x, name=None):
    """Reference ``standard_gamma``: sample Gamma(alpha=x, scale=1)."""
    @primitive(name="standard_gamma")
    def _sg(alpha, key):
        return jax.random.gamma(jax.random.wrap_key_data(key), alpha)

    return _sg(x, jax.random.key_data(state.default_rng.next_key()))


def binomial(count, prob, name=None):
    """Reference ``binomial``: sample Binomial(count, prob) elementwise."""
    @primitive(name="binomial")
    def _bn(n, p, key):
        return jax.random.binomial(
            jax.random.wrap_key_data(key), n.astype(jnp.float32),
            p).astype(jnp.int32)

    return _bn(count, prob,
               jax.random.key_data(state.default_rng.next_key()))


# --- linalg completions ---------------------------------------------------

def eigvals(x, name=None):
    """Reference ``eigvals``: eigenvalues of a general square matrix.
    Host-side numpy (general complex eig has no TPU lowering — the
    reference's kernel is CPU-only too), so eager-mode only."""
    import numpy as np

    a = np.asarray(unwrap(x))
    out_dtype = (np.complex64 if a.dtype in (np.float32, np.complex64)
                 else np.complex128)
    return Tensor(np.linalg.eigvals(a).astype(out_dtype))


@primitive
def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """Reference ``lu_unpack``: split packed LU into (P, L, U)."""
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    # pivots (1-based sequential swaps) -> permutation matrix
    def perm_from_pivots(piv):
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            p = p.at[i].set(pj).at[j].set(pi)
            return p
        p = lax.fori_loop(0, piv.shape[0], body, jnp.arange(m))
        return jnp.eye(m, dtype=lu_data.dtype)[p]

    piv = lu_pivots.astype(jnp.int32)
    if piv.ndim == 1:
        P = perm_from_pivots(piv)
    else:
        P = jax.vmap(perm_from_pivots)(piv.reshape(-1, piv.shape[-1]))
        P = P.reshape(lu_data.shape[:-2] + (m, m))
    return P, L, U


# --- sequence/beam ops ----------------------------------------------------

@primitive
def gather_tree(ids, parents):
    """Reference ``gather_tree``: backtrace beam-search parent pointers.
    ids/parents: [seq_len, batch, beam] -> full sequences."""
    T = ids.shape[0]

    def step(carry, t):
        beams = carry  # [batch, beam] current beam indices
        tok = jnp.take_along_axis(ids[t], beams, axis=-1)
        nxt = jnp.take_along_axis(parents[t], beams, axis=-1)
        return nxt, tok

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return toks[::-1]


@primitive
def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag=True):
    """Reference ``viterbi_decode`` (``text/viterbi_decode.py:26``,
    kernel ``phi/kernels/cpu/viterbi_decode_kernel.cc``): max-sum decode
    over a linear-chain CRF. potentials [B, T, C], transition [C, C];
    with ``include_bos_eos_tag`` the LAST row is the start tag and the
    SECOND-TO-LAST column the stop tag (the reference convention).
    ``lengths`` masks padded timesteps (path positions past a sequence's
    length repeat its final tag). Returns (scores [B], int64 paths
    [B, T])."""
    B, T, C = potentials.shape
    trans = transition
    if include_bos_eos_tag:
        bos = transition[C - 1, :]   # start-tag row
        eos = transition[:, C - 2]   # stop-tag column
    else:
        bos = jnp.zeros((C,), potentials.dtype)
        eos = jnp.zeros((C,), potentials.dtype)

    alpha0 = potentials[:, 0] + bos  # [B, C]
    lens = (None if lengths is None
            else lengths.astype(jnp.int32))

    def step(carry, inp):
        alpha = carry
        emit, t = inp
        scores = alpha[:, :, None] + trans[None]  # [B, C_prev, C]
        best_prev = jnp.argmax(scores, axis=1)    # [B, C]
        new = jnp.max(scores, axis=1) + emit
        if lens is not None:
            live = (t < lens)[:, None]            # padded steps freeze
            new = jnp.where(live, new, alpha)
            best_prev = jnp.where(live, best_prev,
                                  jnp.arange(C)[None, :])
        return new, best_prev

    alpha, back = lax.scan(
        step, alpha0,
        (jnp.swapaxes(potentials[:, 1:], 0, 1),
         jnp.arange(1, T)))
    alpha = alpha + eos
    last = jnp.argmax(alpha, axis=-1)             # [B]
    score = jnp.max(alpha, axis=-1)

    def backstep(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, prev  # ys[t] = tag_t, carry walks backwards

    _, path = lax.scan(backstep, last, back, reverse=True)
    path = jnp.concatenate([jnp.swapaxes(path, 0, 1), last[:, None]],
                           axis=1)
    return score, path.astype(jnp.int64)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Reference ``top_p_sampling``: nucleus sampling over logits
    [B, V]; keeps the smallest prefix of the sorted distribution with
    cumulative probability >= p (candidates below ``threshold`` are also
    dropped), samples within it. ``seed`` makes the draw reproducible;
    otherwise the framework RNG stream (``paddle.seed``) is used.
    Returns (scores, token ids)."""
    import jax.random as jr

    if seed is not None and seed >= 0:
        key_data = jax.random.key_data(jax.random.PRNGKey(seed))
    else:
        key_data = jax.random.key_data(state.default_rng.next_key())

    @primitive(name="top_p_sampling")
    def _tps(logits, p, key):
        return nucleus_sample_jnp(jr.wrap_key_data(key), logits, p,
                                  threshold)

    return _tps(x, ps, key_data)


def nucleus_sample_jnp(key, logits, p, threshold=None):
    """Pure-jnp nucleus-sampling core, shared by the ``top_p_sampling``
    op above and the scanned decode window
    (``models/generation.py``): keeps the smallest sorted prefix with
    cumulative probability >= p, samples within it. Returns
    (scores [B, 1], tokens [B, 1])."""
    import jax.random as jr

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    order = jnp.argsort(-probs, axis=-1)
    sp = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(sp, axis=-1)
    keep = (cum - sp) < jnp.reshape(p, (-1, 1))  # first bucket always kept
    if threshold is not None:
        keep = keep & (sp >= threshold)
        keep = keep.at[:, 0].set(True)           # never drop every token
    masked = jnp.where(keep, sp, 0.0)
    masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
    idx = jr.categorical(key, jnp.log(masked + 1e-30))
    token = jnp.take_along_axis(order, idx[:, None], axis=-1)
    score = jnp.take_along_axis(probs, token, axis=-1)
    return score, token


def frobenius_norm(x, axis=None, keepdim=False, name=None):
    """Reference ``frobenius_norm``: sqrt(sum(x^2)) over ``axis``
    (default: the trailing two dims, the reference kernel's contract).
    Thin wrapper over linalg's ``_fro_norm`` primitive — one home for
    the computation."""
    from .linalg import _fro_norm
    ax = tuple(axis) if axis is not None else (-2, -1)
    return _fro_norm(x, axis=ax, keepdim=keepdim)


@primitive
def identity_loss(x, reduction="none"):
    """Reference ``identity_loss`` op: pass-through loss marker with the
    usual reductions (1=mean, 2=sum, 3/none=identity in the kernel;
    string forms accepted here)."""
    red = {1: "mean", 2: "sum", 3: "none"}.get(reduction, reduction)
    if red == "mean":
        return jnp.mean(x)
    if red == "sum":
        return jnp.sum(x)
    return x


def auc(input, label, stat_pos=None, stat_neg=None, curve="ROC",
        num_thresholds=4095, slide_steps=0, name=None):
    """Reference ``auc`` op (ops.yaml ``auc``; static surface
    ``python/paddle/static/nn/metric.py`` auc): histogram-bucketed AUC
    with running positive/negative stat buffers.

    input: [N, 2] probabilities (column 1 = positive class) or [N, 1];
    label: [N, 1] or [N] in {0, 1}. Returns
    (auc_value, stat_pos_out, stat_neg_out).
    """
    import jax.numpy as jnp

    from ..core.dispatch import apply

    if curve != "ROC":
        raise NotImplementedError(f"auc: curve {curve!r} (ROC only)")
    if slide_steps:
        raise NotImplementedError(
            "auc: slide_steps (sliding-window stats) is not implemented — "
            "pass slide_steps=0 and manage windows by resetting "
            "stat_pos/stat_neg")
    nbins = num_thresholds + 1
    args = [input, label]
    has_stats = stat_pos is not None
    if has_stats:
        args += [stat_pos, stat_neg]

    def impl(pred, lab, *stats):
        p = pred[:, -1] if pred.ndim == 2 else pred
        y = lab.reshape(-1).astype(jnp.float32)
        idx = jnp.clip((p * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
        pos = jnp.zeros((nbins,), jnp.float32).at[idx].add(y)
        neg = jnp.zeros((nbins,), jnp.float32).at[idx].add(1.0 - y)
        if stats:
            pos = pos + stats[0].reshape(-1).astype(jnp.float32)
            neg = neg + stats[1].reshape(-1).astype(jnp.float32)
        # trapezoid in ROC space, thresholds descending: x = FP, y = TP;
        # area = sum dFP * (TP - dTP/2) (reference auc kernel)
        tot_pos = jnp.cumsum(pos[::-1])
        tot_neg = jnp.cumsum(neg[::-1])
        d_tp = jnp.diff(jnp.concatenate([jnp.zeros(1), tot_pos]))
        d_fp = jnp.diff(jnp.concatenate([jnp.zeros(1), tot_neg]))
        area = jnp.sum(d_fp * (tot_pos - 0.5 * d_tp))
        denom = jnp.maximum(tot_pos[-1] * tot_neg[-1], 1e-12)
        return area / denom, pos.astype(jnp.int64), neg.astype(jnp.int64)

    return apply("auc", impl, *args)

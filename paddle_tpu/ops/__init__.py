"""paddle_tpu.ops — the op library.

Analog of the reference's PHI op surface (SURVEY C11/C15,
``paddle/phi/api/yaml/ops.yaml`` 297 ops) exposed with paddle's python names
(``python/paddle/tensor/``). Also installs Tensor methods/operators — the
analog of the generated pybind method table
(``paddle/fluid/pybind/eager_method.cc``).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor, Parameter
from ..core.dispatch import apply, primitive, unwrap

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .array import *  # noqa: F401,F403
from .extra import *  # noqa: F401,F403
from .special import *  # noqa: F401,F403

from . import creation, math, manipulation, logic, linalg, random  # noqa: F401
from . import array, extra, special  # noqa: F401


# ---- indexing ------------------------------------------------------------

def _getitem(x: Tensor, idx):
    if not isinstance(idx, tuple):
        idx = (idx,)
    tensor_slots = []
    spec = []
    for it in idx:
        if isinstance(it, Tensor):
            if it.dtype == np.dtype(bool):
                # boolean mask: dynamic shape — concretize (eager only)
                spec.append(("c", np.asarray(it._read())))
            else:
                spec.append(("t", len(tensor_slots)))
                tensor_slots.append(it)
        elif isinstance(it, (list, np.ndarray)) and not isinstance(it, str):
            spec.append(("c", np.asarray(it)))
        else:
            spec.append(("c", it))

    def fn(v, *ts):
        items = tuple(ts[s[1]] if s[0] == "t" else s[1] for s in spec)
        return v[items]

    return apply("getitem", fn, x, *tensor_slots)


def _setitem(x: Tensor, idx, value):
    if not isinstance(idx, tuple):
        idx = (idx,)
    tensor_slots = []
    spec = []
    for it in idx:
        if isinstance(it, Tensor):
            if it.dtype == np.dtype(bool):
                spec.append(("c", np.asarray(it._read())))
            else:
                spec.append(("t", len(tensor_slots)))
                tensor_slots.append(it)
        elif isinstance(it, (list, np.ndarray)) and not isinstance(it, str):
            spec.append(("c", np.asarray(it)))
        else:
            spec.append(("c", it))
    val_is_tensor = isinstance(value, Tensor)

    def fn(v, *ts):
        items = tuple(ts[s[1]] if s[0] == "t" else s[1] for s in spec)
        val = ts[-1] if val_is_tensor else jnp.asarray(value, v.dtype)
        return v.at[items].set(val.astype(v.dtype) if hasattr(val, "astype") else val)

    args = tensor_slots + ([value] if val_is_tensor else [])
    out = apply("setitem", fn, x, *args)
    x._adopt(out)
    return x


# ---- in-place variants (adopt result; analog of paddle *_ ops) ----------

def _make_inplace(op):
    def method(self, *a, **k):
        out = op(self, *a, **k)
        self._adopt(out)
        return self
    return method


# ---- install Tensor methods ---------------------------------------------

def _swap(fn):
    return lambda self, other: fn(to_tensor(other) if not isinstance(other, Tensor) else other, self)


_METHODS = {
    # math
    "add": add, "subtract": subtract, "multiply": multiply, "divide": divide,
    "floor_divide": floor_divide, "mod": mod, "remainder": mod, "pow": pow,
    "matmul": matmul, "sqrt": sqrt, "rsqrt": rsqrt, "exp": exp, "expm1": expm1,
    "log": log, "log2": log2, "log10": log10, "log1p": log1p, "abs": abs,
    "neg": neg, "sign": sign, "floor": floor, "ceil": ceil, "round": round,
    "trunc": trunc, "frac": frac, "sin": sin, "cos": cos, "tan": tan,
    "asin": asin, "acos": acos, "atan": atan, "sinh": sinh, "cosh": cosh,
    "tanh": tanh, "asinh": asinh, "acosh": acosh, "atanh": atanh, "erf": erf,
    "erfinv": erfinv, "reciprocal": reciprocal, "square": square,
    "maximum": maximum, "minimum": minimum, "fmax": fmax, "fmin": fmin,
    "clip": clip, "lerp": lerp, "scale": scale, "atan2": atan2,
    "logsumexp": logsumexp, "logaddexp": logaddexp, "nan_to_num": nan_to_num,
    "cumsum": cumsum, "cumprod": cumprod, "digamma": digamma, "lgamma": lgamma,
    "hypot": hypot, "heaviside": heaviside, "angle": angle, "conj": conj,
    "trace": trace, "diagonal": diagonal, "kron": kron, "inner": inner,
    "outer": outer, "addmm": addmm,
    # reductions
    "sum": sum, "mean": mean, "max": max, "min": min, "prod": prod,
    "amax": amax, "amin": amin, "std": std, "var": var, "median": median,
    "nanmean": nanmean, "nansum": nansum, "quantile": quantile,
    "argmax": argmax, "argmin": argmin, "count_nonzero": count_nonzero,
    "all": all, "any": any, "norm": norm,
    # logic
    "equal": equal, "not_equal": not_equal, "greater_than": greater_than,
    "greater_equal": greater_equal, "less_than": less_than,
    "less_equal": less_equal, "equal_all": equal_all,
    "logical_and": logical_and, "logical_or": logical_or,
    "logical_xor": logical_xor, "logical_not": logical_not,
    "bitwise_and": bitwise_and, "bitwise_or": bitwise_or,
    "bitwise_xor": bitwise_xor, "bitwise_not": bitwise_not,
    "isnan": isnan, "isinf": isinf, "isfinite": isfinite, "isclose": isclose,
    "allclose": allclose,
    # manipulation
    "reshape": reshape, "reshape_": reshape_, "transpose": transpose,
    "flatten": flatten, "squeeze": squeeze, "unsqueeze": unsqueeze,
    "unsqueeze_": unsqueeze_, "split": split, "chunk": chunk, "tile": tile,
    "expand": expand, "expand_as": expand_as, "broadcast_to": broadcast_to,
    "flip": flip, "roll": roll, "gather": gather, "gather_nd": gather_nd,
    "scatter": scatter, "scatter_nd_add": scatter_nd_add,
    "index_select": index_select, "index_sample": index_sample,
    "index_add": index_add, "masked_select": masked_select,
    "masked_fill": masked_fill, "where": where,
    "take_along_axis": take_along_axis, "put_along_axis": put_along_axis,
    "repeat_interleave": repeat_interleave, "unbind": unbind,
    "cast": cast, "astype": astype, "topk": topk, "sort": sort,
    "argsort": argsort, "nonzero": nonzero, "unique": unique,
    "tril": tril, "triu": triu, "diag": diag, "moveaxis": moveaxis,
    "swapaxes": swapaxes, "unstack": unstack, "bincount": bincount,
    "histogram": histogram, "searchsorted": searchsorted,
    "kthvalue": kthvalue, "mode": mode, "view": view,
    "as_strided": as_strided, "masked_scatter": masked_scatter,
    "index_put": index_put, "strided_slice": strided_slice,
    "slice": slice, "pad": pad, "flatten_": _make_inplace(flatten),
    # linalg
    "dot": dot, "mm": mm, "bmm": bmm, "mv": mv, "t": t, "cross": cross,
    "cholesky": cholesky, "inverse": inverse, "pinv": pinv, "solve": solve,
    "det": det, "slogdet": slogdet, "matrix_power": matrix_power,
    "qr": qr, "svd": svd, "eigh": eigh, "eig": eig, "lu": lu,
    "cholesky_solve": cholesky_solve, "triangular_solve": triangular_solve,
    "tensordot": tensordot,
    # creation-ish
    "zeros_like": zeros_like, "ones_like": ones_like, "full_like": full_like,
    "clone": creation.clone, "numel": numel, "real": real, "imag": imag,
    # random in-place
    "exponential_": random.exponential_, "normal_": random.normal_,
    "uniform_": random.uniform_,
}

_INPLACE_BASE = ["add", "subtract", "multiply", "divide", "clip", "scale",
                 "floor", "ceil", "exp", "sqrt", "rsqrt", "reciprocal",
                 "round", "abs", "cast", "tanh", "squeeze"]


def _install():
    for name, fn in _METHODS.items():
        setattr(Tensor, name, fn)
    for name in _INPLACE_BASE:
        setattr(Tensor, name + "_", _make_inplace(_METHODS[name]))
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.__add__ = add
    Tensor.__radd__ = _swap(add)
    Tensor.__sub__ = subtract
    Tensor.__rsub__ = _swap(subtract)
    Tensor.__mul__ = multiply
    Tensor.__rmul__ = _swap(multiply)
    Tensor.__truediv__ = divide
    Tensor.__rtruediv__ = _swap(divide)
    Tensor.__floordiv__ = floor_divide
    Tensor.__rfloordiv__ = _swap(floor_divide)
    Tensor.__mod__ = mod
    Tensor.__rmod__ = _swap(mod)
    Tensor.__pow__ = pow
    Tensor.__rpow__ = _swap(pow)
    Tensor.__matmul__ = matmul
    Tensor.__rmatmul__ = _swap(matmul)
    Tensor.__neg__ = neg
    Tensor.__abs__ = abs
    Tensor.__invert__ = bitwise_not
    Tensor.__eq__ = equal
    Tensor.__ne__ = not_equal
    Tensor.__lt__ = less_than
    Tensor.__le__ = less_equal
    Tensor.__gt__ = greater_than
    Tensor.__ge__ = greater_equal
    Tensor.__and__ = bitwise_and
    Tensor.__or__ = bitwise_or
    Tensor.__xor__ = bitwise_xor


_install()

"""TensorArray ops (SURVEY C8 — reference ``python/paddle/tensor/array.py``
array_read/array_write/array_length/create_array over the C++
TensorArray). Eager-first framing: a TensorArray is a Python list of
Tensors (exactly what the reference's dygraph mode does); inside
``jit.to_static`` capture the list ops trace like any other Python
structure, with static indices."""
from __future__ import annotations

from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def create_array(dtype="float32", initialized_list=None):
    """Reference ``create_array``."""
    arr = []
    if initialized_list is not None:
        for t in initialized_list:
            arr.append(t if isinstance(t, Tensor) else Tensor(t))
    return arr


def array_length(array) -> int:
    """Reference ``array_length``."""
    return len(array)


def array_write(x, i, array=None):
    """Reference ``array_write``: write ``x`` at index ``i`` (appending
    when ``i == len``)."""
    if array is None:
        array = []
    i = int(unwrap(i))
    if i > len(array):
        raise IndexError(
            f"array_write index {i} out of range (length {len(array)})")
    x = x if isinstance(x, Tensor) else Tensor(x)
    if i == len(array):
        array.append(x)
    else:
        array[i] = x
    return array


def array_read(array, i) -> Tensor:
    """Reference ``array_read``."""
    i = int(unwrap(i))
    if not 0 <= i < len(array):
        raise IndexError(
            f"array_read index {i} out of range (length {len(array)})")
    return array[i]


__all__ = ["create_array", "array_length", "array_write", "array_read"]

"""Random ops over the functional JAX PRNG with a mutable global seed
(paddle.seed analog; reference generator lives in
``paddle/phi/core/generator.h``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core.dtype import convert_dtype
from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def _dt(dtype):
    d = convert_dtype(dtype)
    return state.DEFAULT_DTYPE if d is None else d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._read()))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


def rand(shape, dtype=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    key = state.default_rng.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=_dt(dtype)))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = jnp.asarray(unwrap(mean)), jnp.asarray(unwrap(std))
        shp = jnp.broadcast_shapes(m.shape, s.shape)
        key = state.default_rng.next_key()
        return Tensor(m + s * jax.random.normal(key, shp, dtype=state.DEFAULT_DTYPE))
    key = state.default_rng.next_key()
    return Tensor(mean + std * jax.random.normal(
        key, _shape(shape if shape is not None else [1]),
        dtype=state.DEFAULT_DTYPE))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = (jax.random.PRNGKey(seed) if seed else state.default_rng.next_key())
    return Tensor(jax.random.uniform(
        key, _shape(shape), dtype=_dt(dtype),
        minval=float(unwrap(min)), maxval=float(unwrap(max))))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    key = state.default_rng.next_key()
    return Tensor(jax.random.randint(
        key, _shape(shape), int(low), int(high)).astype(convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    return randint(low, high, shape=x.shape, dtype=dtype or x.dtype)


def randperm(n, dtype="int64"):
    key = state.default_rng.next_key()
    return Tensor(jax.random.permutation(key, n).astype(convert_dtype(dtype)))


def bernoulli(x):
    key = state.default_rng.next_key()
    p = unwrap(x)
    return Tensor(jax.random.bernoulli(key, p, p.shape).astype(p.dtype))


def poisson(x):
    key = state.default_rng.next_key()
    lam = unwrap(x)
    return Tensor(jax.random.poisson(key, lam, lam.shape).astype(lam.dtype))


def multinomial(x, num_samples=1, replacement=False):
    key = state.default_rng.next_key()
    p = unwrap(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        batch = p.shape[:-1]
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(num_samples, *batch))
        out = jnp.moveaxis(out, 0, -1) if batch else out
        return Tensor(out.astype(jnp.int64))
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(key, p.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int64))


def rand_like(x, dtype=None):
    return rand(x.shape, dtype=dtype or x.dtype)


def randn_like(x, dtype=None):
    return randn(x.shape, dtype=dtype or x.dtype)


def exponential_(x, lam=1.0):
    key = state.default_rng.next_key()
    out = jax.random.exponential(key, tuple(x.shape)).astype(x.dtype) / lam
    x._write(out)
    return x


def normal_(x, mean=0.0, std=1.0):
    key = state.default_rng.next_key()
    out = mean + std * jax.random.normal(key, tuple(x.shape)).astype(x.dtype)
    x._write(out)
    return x


def uniform_(x, min=-1.0, max=1.0):
    key = state.default_rng.next_key()
    out = jax.random.uniform(key, tuple(x.shape), minval=min,
                             maxval=max).astype(x.dtype)
    x._write(out)
    return x

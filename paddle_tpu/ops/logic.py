"""Comparison & logical ops. Analog of ``python/paddle/tensor/logic.py``
(reference)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor


@primitive
def equal(x, y):
    return jnp.equal(x, y)


@primitive
def not_equal(x, y):
    return jnp.not_equal(x, y)


@primitive
def greater_than(x, y):
    return jnp.greater(x, y)


@primitive
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@primitive
def less_than(x, y):
    return jnp.less(x, y)


@primitive
def less_equal(x, y):
    return jnp.less_equal(x, y)


def equal_all(x, y):
    return Tensor(jnp.array_equal(unwrap(x), unwrap(y)))


@primitive
def logical_and(x, y):
    return jnp.logical_and(x, y)


@primitive
def logical_or(x, y):
    return jnp.logical_or(x, y)


@primitive
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@primitive
def logical_not(x):
    return jnp.logical_not(x)


@primitive
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@primitive
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@primitive
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@primitive
def bitwise_not(x):
    return jnp.bitwise_not(x)


@primitive
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@primitive
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


@primitive
def _all(x, axis, keepdim):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _all(x, axis=axis, keepdim=keepdim)


@primitive
def _any(x, axis, keepdim):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _any(x, axis=axis, keepdim=keepdim)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x):
    return Tensor(jnp.asarray(x.size == 0))

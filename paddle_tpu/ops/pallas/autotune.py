"""Kernel autotuning (SURVEY C14 — reference
``python/paddle/incubate/autotune.py`` set_config + the cached kernel
autotune of ``paddle/phi/kernels/autotune/switch_autotune.h``,
``cache.h``).

TPU shape: Pallas kernels have block-size free parameters; the autotuner
times each candidate configuration on the real shapes the model runs
(two calls per candidate — the first compiles, the second measures a
host-synced median of repeats) and persists the winner per
(device kind, op, shape signature) in a JSON cache so later processes
skip the sweep. Disabled by default (the reference's autotune is also
opt-in); enable with ``paddle_tpu.incubate.autotune.set_config(
{"kernel": {"enable": True}})`` or ``PDTPU_AUTOTUNE=1``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional, Sequence

_config = {"kernel": {"enable": os.environ.get("PDTPU_AUTOTUNE") == "1",
                      "tuning_range": [1, 10]}}
_cache: Optional[dict] = None
_CACHE_PATH = os.path.join(
    os.environ.get("PDTPU_CACHE_DIR",
                   os.path.expanduser("~/.cache/paddle_tpu")),
    "autotune.json")


def set_config(config=None):
    """Reference ``incubate/autotune.py set_config`` (kernel section)."""
    if config is None:
        _config["kernel"]["enable"] = True
        return
    if isinstance(config, str):  # file form
        with open(config) as f:
            config = json.load(f)
    if "kernel" in config:
        _config["kernel"].update(config["kernel"])


def enabled() -> bool:
    return bool(_config["kernel"]["enable"])


def _load_cache() -> dict:
    global _cache
    if _cache is None:
        try:
            with open(_CACHE_PATH) as f:
                _cache = json.load(f)
        except Exception:
            _cache = {}
    return _cache


def _store_cache():
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        with open(_CACHE_PATH, "w") as f:
            json.dump(_cache, f)
    except Exception:
        pass  # cache is an optimization, never an error


def _device_kind() -> str:
    import jax
    d = jax.devices()[0]
    return str(getattr(d, "device_kind", d.platform))


def _same_candidate(a, b):
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return list(a) == list(b)
    return a == b


def autotune(op: str, signature: str, candidates: Sequence,
             run: Callable, repeats: int = 3):
    """Pick the fastest candidate for ``run(candidate)`` and cache it.

    ``run`` must execute the kernel to completion (host-synced) — it is
    called once per candidate for warmup/compile and ``repeats`` times
    for timing. Failing candidates (e.g. VMEM overflow) are skipped.
    Returns the winning candidate (cached on later calls)."""
    key = f"{_device_kind()}|{op}|{signature}"
    cache = _load_cache()
    if key in cache:
        # the cached WINNER (value, not index: an index would silently
        # remap whenever the candidate list evolves); honor it only while
        # it is still a known candidate
        cached = cache[key]
        for cand in candidates:
            if _same_candidate(cand, cached):
                return cand
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            run(cand)  # compile + warm
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                run(cand)
                ts.append(time.perf_counter() - t0)
            t = sorted(ts)[len(ts) // 2]
        except Exception:
            continue
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        raise RuntimeError(f"autotune: every candidate failed for {op} "
                           f"{signature}")
    cache[key] = list(best) if isinstance(best, (list, tuple)) else best
    _store_cache()
    return best


__all__ = ["set_config", "enabled", "autotune"]

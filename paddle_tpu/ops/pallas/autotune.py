"""Kernel autotuning (SURVEY C14 — reference
``python/paddle/incubate/autotune.py`` set_config + the cached kernel
autotune of ``paddle/phi/kernels/autotune/switch_autotune.h``,
``cache.h``).

TPU shape: Pallas kernels have block-size free parameters; the autotuner
times each candidate configuration on the real shapes the model runs
and persists the winner per (device kind, op, shape signature) in a
JSON cache so later processes skip the sweep.

Tuned entries: ``flash_attention`` (forward block_q, block_k — see
flash_attention._autotuned_blocks), ``flash_attention_bwd`` (the FUSED
backward kernel's block pair, tuned separately over backward-specific
candidates — the backward's full-row q/do/dq VMEM buffers plus dk/dv
accumulators admit different winners than the forward, and the old
shared entry let the backward inherit forward-biased blocks — see
flash_attention._autotuned_bwd_blocks), ``paged_attention_ppb``
(pages_per_block of the ragged paged-KV serving kernel — see
paged_attention.pick_pages_per_block; candidates are powers of two
bounded by the block-table width and a VMEM cap, cache hits apply under
a trace, sweeps run on synthetic decode shapes when enabled),
``fused_optimizer_rows`` (row-block of the fused optimizer update —
fused_optimizer.pick_rows), ``quant_matmul_blocks`` ((bm, bn) output
tiling of the fused weight-only int8 matmul —
quant_matmul.pick_blocks), ``fused_decode_qkv_rows`` (row block of the
decode megakernel's norm+QKV+rope+paged-append ingress kernel —
fused_decode_qkv.pick_qkv_rows; candidates VMEM-capped, default one
block covering the whole decode batch), ``fused_decode_mlp_rows``
(row block of the megakernel's out-proj+residual+MLP egress kernel —
fused_decode_mlp.pick_mlp_rows) and ``fused_residual_norm_rows`` (row
block of the training glue kernels' fused residual-add+norm fwd/bwd
pair — fused_residual_norm.pick_glue_rows; the sweep times a full
grad-through-custom_vjp round trip since the bwd kernel replays the
same tile walk).

LIMITATION (measured, round 4): the sweep times candidates in an
isolated chained program; the winner inside a REAL train step can
differ by a few percent because XLA fuses/schedules the kernel
differently in context (e.g. the GPT-124M step runs fastest with
(256,512) although the isolated fwd+bwd chain ranks (512,1024) first).
The cache stores VALUES, so an end-to-end-measured winner can be pinned
by writing it into the cache file — bench.py ships pinned winners for
its two model shapes in benchmarks/measured/autotune.json.

Disabled by default (the reference's autotune is also opt-in); enable
with ``paddle_tpu.incubate.autotune.set_config({"kernel": {"enable":
True}})`` or ``PDTPU_AUTOTUNE=1``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional, Sequence

_config = {"kernel": {"enable": os.environ.get("PDTPU_AUTOTUNE") == "1",
                      "tuning_range": [1, 10]}}
_cache: Optional[dict] = None
_CACHE_PATH = os.path.join(
    os.environ.get("PDTPU_CACHE_DIR",
                   os.path.expanduser("~/.cache/paddle_tpu")),
    "autotune.json")


def set_config(config=None):
    """Reference ``incubate/autotune.py set_config`` (kernel section)."""
    if config is None:
        _config["kernel"]["enable"] = True
        return
    if isinstance(config, str):  # file form
        with open(config) as f:
            config = json.load(f)
    if "kernel" in config:
        _config["kernel"].update(config["kernel"])


def enabled() -> bool:
    return bool(_config["kernel"]["enable"])


def _load_cache() -> dict:
    global _cache
    if _cache is None:
        try:
            with open(_CACHE_PATH) as f:
                _cache = json.load(f)
        except Exception:
            _cache = {}
    return _cache


def _store_cache():
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        with open(_CACHE_PATH, "w") as f:
            json.dump(_cache, f)
    except Exception:
        pass  # cache is an optimization, never an error


def _device_kind() -> str:
    import jax
    d = jax.devices()[0]
    return str(getattr(d, "device_kind", d.platform))


def _same_candidate(a, b):
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return list(a) == list(b)
    return a == b


def autotune(op: str, signature: str, candidates: Sequence,
             run: Callable, repeats: int = 3, measure: Callable = None,
             validate: Callable = None):
    """Pick the fastest candidate for ``run(candidate)`` and cache it.

    ``run`` must execute the kernel to completion (host-synced) — it is
    called once per candidate for warmup/compile and ``repeats`` times
    for timing. Failing candidates (e.g. VMEM overflow) are skipped.
    Returns the winning candidate (cached on later calls).

    ``measure``: optional ``cand -> seconds`` that owns its own timing
    (e.g. the dispatch-free scan-slope of benchmarks/calibrate.py —
    wall-timing individual dispatches over a network-attached chip is
    jitter-dominated and picks wrong winners). When given, ``run`` is
    not used. ``validate``: optional ``cand -> None`` called on each
    prospective winner in the caller's REAL execution context; if it
    raises (e.g. scoped-vmem overflow that the measuring context did
    not trigger), the candidate is discarded and the next-best wins."""
    key = f"{_device_kind()}|{op}|{signature}"
    cache = _load_cache()
    if key in cache:
        # the cached WINNER (value, not index: an index would silently
        # remap whenever the candidate list evolves); honor it only while
        # it is still a known candidate
        cached = cache[key]
        for cand in candidates:
            if _same_candidate(cand, cached):
                return cand
    scored = []
    for cand in candidates:
        try:
            if measure is not None:
                t = measure(cand)
            else:
                run(cand)  # compile + warm
                ts = []
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    run(cand)
                    ts.append(time.perf_counter() - t0)
                t = sorted(ts)[len(ts) // 2]
        except Exception:
            continue
        if t != float("inf"):  # inf = below timing resolution, not a score
            scored.append((t, cand))
    scored.sort(key=lambda tc: tc[0])
    best = None
    for _, cand in scored:
        if validate is not None:
            try:
                validate(cand)
            except Exception:
                continue
        best = cand
        break
    if best is None:
        raise RuntimeError(f"autotune: every candidate failed for {op} "
                           f"{signature}")
    cache[key] = list(best) if isinstance(best, (list, tuple)) else best
    _store_cache()
    return best


__all__ = ["set_config", "enabled", "autotune"]

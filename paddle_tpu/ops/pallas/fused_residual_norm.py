"""Fused residual-add + normalization training glue kernels (ISSUE 19).

The training step's per-layer glue chain — residual add feeding a
pre/post-norm — sits between the flash and matmul kernels as separate
dispatches (calibrate.kernel_breakdown's glue share).  Each kernel here
runs one row-blocked pass computing BOTH the residual sum and its
normalized value, saving fp32 stats for a fused backward that replays
the exact tile walk (the ``flash_attention_bwd_jnp`` discipline):

  ``fused_residual_layer_norm``  (res, normed) = (x+y, LN(x+y)*w+b)
  ``fused_residual_rms_norm``    (res, normed) = (x+y, RMS(x+y)*w)

Both are ``jax.custom_vjp``: the backward kernel consumes the residual
stream cotangent AND the normed cotangent in one pass and emits the
shared input cotangent (d(x) == d(y)) plus tile-aligned dw/db partials
summed on the host, exactly like ``norms.py``.

Every kernel has an unjitted twin (``*_fwd_twin`` / ``*_bwd_twin``)
walking identical row blocks with the block math under ``jax.jit`` —
bitwise vs interpret mode (fused_decode_mlp's twin contract).  Row
block is an autotune entry (``fused_residual_norm_rows`` —
``pick_glue_rows``).

Wired into the GPT/LLaMA/BERT blocks behind the ``train_glue_fusion``
flag (default OFF: the standalone Pallas LN measured as a fusion
BARRIER in-context — +6 ms/step on the GPT-124M bench, see
nn/functional/norm.py — so the fused glue path ships dark until the
TPU round prices it end-to-end, the serving_megakernel precedent).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def default_rows(rows):
    return min(256, rows)


def _pad_rows(x, br):
    pad = (-x.shape[0]) % br
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    return x


def _resolve_interpret(interpret):
    if interpret is None:
        from . import use_interpret
        return use_interpret()
    return bool(interpret)


# --------------------------------------------------------------------------
# block math — shared VERBATIM by the Pallas kernels (on loaded tiles)
# and the jnp twins (jitted per row block), so parity is structural
# --------------------------------------------------------------------------
def _rln_fwd_block(xv, yv, w, b, *, eps):
    """One row tile: residual add (input dtype, the blocks' op order),
    then LayerNorm with fp32 stats.  Returns (res, normed, mean, rstd)."""
    r = xv + yv
    r32 = r.astype(jnp.float32)
    mean = jnp.mean(r32, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(r32 - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o = ((r32 - mean) * rstd * w.astype(jnp.float32)
         + b.astype(jnp.float32)).astype(r.dtype)
    return r, o, mean, rstd


def _rln_bwd_block(rv, w, mean, rstd, drv, gv, *, eps):
    """One row tile of the fused backward: d = dres + LN_dx(dnormed),
    the SHARED cotangent of both adders (d(x) == d(y) == d), plus this
    tile's dw/db partials (fp32 row sums)."""
    del eps  # stats are saved; eps only shapes them in forward
    r32 = rv.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    g = gv.astype(jnp.float32)
    dr = drv.astype(jnp.float32)
    xhat = (r32 - mean) * rstd
    wg = g * w32
    c1 = jnp.mean(wg, axis=1, keepdims=True)
    c2 = jnp.mean(wg * xhat, axis=1, keepdims=True)
    d = (dr + rstd * (wg - c1 - xhat * c2)).astype(rv.dtype)
    return d, jnp.sum(g * xhat, axis=0), jnp.sum(g, axis=0)


def _rrms_fwd_block(xv, yv, w, *, eps):
    r = xv + yv
    r32 = r.astype(jnp.float32)
    ms = jnp.mean(jnp.square(r32), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o = (r32 * rstd * w.astype(jnp.float32)).astype(r.dtype)
    return r, o, rstd


def _rrms_bwd_block(rv, w, rstd, drv, gv, *, eps):
    del eps
    r32 = rv.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    g = gv.astype(jnp.float32)
    dr = drv.astype(jnp.float32)
    xhat = r32 * rstd
    wg = g * w32
    c = jnp.mean(wg * xhat, axis=1, keepdims=True)
    d = (dr + rstd * (wg - xhat * c)).astype(rv.dtype)
    return d, jnp.sum(g * xhat, axis=0)


# --------------------------------------------------------------------------
# kernel/twin plumbing (row-blocked; weights ride block-invariant)
# --------------------------------------------------------------------------
def _rows_for(n_valid, rows):
    return default_rows(n_valid) if rows is None else int(rows)


def _row_spec(br, h):
    return pl.BlockSpec((br, h), lambda i: (i, 0))


def _stat_spec(br):
    return pl.BlockSpec((br, 1), lambda i: (i, 0))


def _full_spec(h):
    return pl.BlockSpec((1, h), lambda i: (0, 0))


def _tile_spec(h):
    # tile-aligned (grid, 8, h) partial accumulator (norms.py layout)
    return pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0))


def fused_residual_layer_norm_fwd(x, y, w, b, *, eps=1e-5, rows=None,
                                  interpret=None):
    """Kernel forward on 2-D [rows, h]: (res, normed, mean, rstd)."""
    n, h = x.shape
    br = _rows_for(n, rows)
    xp, yp = _pad_rows(x, br), _pad_rows(y, br)
    grid = (xp.shape[0] // br,)

    def kernel(x_ref, y_ref, w_ref, b_ref, r_ref, o_ref, m_ref, s_ref):
        r, o, mean, rstd = _rln_fwd_block(
            x_ref[:], y_ref[:], w_ref[:], b_ref[:], eps=eps)
        r_ref[:] = r
        o_ref[:] = o
        m_ref[:] = mean
        s_ref[:] = rstd

    r, o, mean, rstd = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[_row_spec(br, h), _row_spec(br, h),
                  _full_spec(h), _full_spec(h)],
        out_specs=[_row_spec(br, h), _row_spec(br, h),
                   _stat_spec(br), _stat_spec(br)],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(xp, yp, w[None, :], b[None, :])
    return r[:n], o[:n], mean[:n], rstd[:n]


def fused_residual_layer_norm_fwd_twin(x, y, w, b, *, eps=1e-5,
                                       rows=None):
    """Twin of the forward kernel: identical padding, identical per-block
    math under ``jax.jit`` (shared FMA-fusion semantics), concatenated
    back — bitwise vs interpret mode."""
    n, h = x.shape
    br = _rows_for(n, rows)
    xp, yp = _pad_rows(x, br), _pad_rows(y, br)
    jfn = jax.jit(functools.partial(_rln_fwd_block, eps=eps))
    parts = [jfn(xp[i * br:(i + 1) * br], yp[i * br:(i + 1) * br],
                 w[None, :], b[None, :])
             for i in range(xp.shape[0] // br)]
    return tuple(jnp.concatenate(ps, axis=0)[:n] for ps in zip(*parts))


def fused_residual_layer_norm_bwd(res, w, mean, rstd, dres, dnormed, *,
                                  eps=1e-5, rows=None, interpret=None):
    """Kernel backward replaying the forward's tile walk: (d, dw, db)
    with d the SHARED x/y cotangent."""
    n, h = res.shape
    br = _rows_for(n, rows)
    rp = _pad_rows(res, br)
    pad = rp.shape[0] - n
    mp = jnp.pad(mean, ((0, pad), (0, 0)))
    sp = jnp.pad(rstd, ((0, pad), (0, 0)))
    drp, gp = _pad_rows(dres, br), _pad_rows(dnormed, br)
    grid = (rp.shape[0] // br,)

    def kernel(r_ref, w_ref, m_ref, s_ref, dr_ref, g_ref,
               d_ref, dwp_ref, dbp_ref):
        d, dw_p, db_p = _rln_bwd_block(
            r_ref[:], w_ref[:], m_ref[:], s_ref[:], dr_ref[:], g_ref[:],
            eps=eps)
        d_ref[:] = d
        dwp_ref[0] = jnp.broadcast_to(dw_p[None, :], (8, h))
        dbp_ref[0] = jnp.broadcast_to(db_p[None, :], (8, h))

    d, dwp, dbp = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[_row_spec(br, h), _full_spec(h), _stat_spec(br),
                  _stat_spec(br), _row_spec(br, h), _row_spec(br, h)],
        out_specs=[_row_spec(br, h), _tile_spec(h), _tile_spec(h)],
        out_shape=[jax.ShapeDtypeStruct(rp.shape, res.dtype),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(rp, w[None, :], mp, sp, drp, gp)
    return (d[:n], jnp.sum(dwp[:, 0], axis=0).astype(w.dtype),
            jnp.sum(dbp[:, 0], axis=0).astype(w.dtype))


def fused_residual_layer_norm_bwd_twin(res, w, mean, rstd, dres,
                                       dnormed, *, eps=1e-5, rows=None):
    """Backward twin replaying the EXACT tile walk (per-block jitted
    math, per-block dw/db partials, host sum in the kernel's order)."""
    n, h = res.shape
    br = _rows_for(n, rows)
    rp = _pad_rows(res, br)
    pad = rp.shape[0] - n
    mp = jnp.pad(mean, ((0, pad), (0, 0)))
    sp = jnp.pad(rstd, ((0, pad), (0, 0)))
    drp, gp = _pad_rows(dres, br), _pad_rows(dnormed, br)
    jfn = jax.jit(functools.partial(_rln_bwd_block, eps=eps))
    ds, dws, dbs = [], [], []
    for i in range(rp.shape[0] // br):
        sl = slice(i * br, (i + 1) * br)
        d, dw_p, db_p = jfn(rp[sl], w[None, :], mp[sl], sp[sl],
                            drp[sl], gp[sl])
        ds.append(d)
        dws.append(dw_p)
        dbs.append(db_p)
    return (jnp.concatenate(ds, axis=0)[:n],
            jnp.sum(jnp.stack(dws), axis=0).astype(w.dtype),
            jnp.sum(jnp.stack(dbs), axis=0).astype(w.dtype))


def fused_residual_rms_norm_fwd(x, y, w, *, eps=1e-6, rows=None,
                                interpret=None):
    """Kernel forward on 2-D [rows, h]: (res, normed, rstd)."""
    n, h = x.shape
    br = _rows_for(n, rows)
    xp, yp = _pad_rows(x, br), _pad_rows(y, br)
    grid = (xp.shape[0] // br,)

    def kernel(x_ref, y_ref, w_ref, r_ref, o_ref, s_ref):
        r, o, rstd = _rrms_fwd_block(x_ref[:], y_ref[:], w_ref[:],
                                     eps=eps)
        r_ref[:] = r
        o_ref[:] = o
        s_ref[:] = rstd

    r, o, rstd = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[_row_spec(br, h), _row_spec(br, h), _full_spec(h)],
        out_specs=[_row_spec(br, h), _row_spec(br, h), _stat_spec(br)],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(xp, yp, w[None, :])
    return r[:n], o[:n], rstd[:n]


def fused_residual_rms_norm_fwd_twin(x, y, w, *, eps=1e-6, rows=None):
    n, h = x.shape
    br = _rows_for(n, rows)
    xp, yp = _pad_rows(x, br), _pad_rows(y, br)
    jfn = jax.jit(functools.partial(_rrms_fwd_block, eps=eps))
    parts = [jfn(xp[i * br:(i + 1) * br], yp[i * br:(i + 1) * br],
                 w[None, :])
             for i in range(xp.shape[0] // br)]
    return tuple(jnp.concatenate(ps, axis=0)[:n] for ps in zip(*parts))


def fused_residual_rms_norm_bwd(res, w, rstd, dres, dnormed, *,
                                eps=1e-6, rows=None, interpret=None):
    n, h = res.shape
    br = _rows_for(n, rows)
    rp = _pad_rows(res, br)
    sp = jnp.pad(rstd, ((0, rp.shape[0] - n), (0, 0)))
    drp, gp = _pad_rows(dres, br), _pad_rows(dnormed, br)
    grid = (rp.shape[0] // br,)

    def kernel(r_ref, w_ref, s_ref, dr_ref, g_ref, d_ref, dwp_ref):
        d, dw_p = _rrms_bwd_block(r_ref[:], w_ref[:], s_ref[:],
                                  dr_ref[:], g_ref[:], eps=eps)
        d_ref[:] = d
        dwp_ref[0] = jnp.broadcast_to(dw_p[None, :], (8, h))

    d, dwp = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[_row_spec(br, h), _full_spec(h), _stat_spec(br),
                  _row_spec(br, h), _row_spec(br, h)],
        out_specs=[_row_spec(br, h), _tile_spec(h)],
        out_shape=[jax.ShapeDtypeStruct(rp.shape, res.dtype),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(rp, w[None, :], sp, drp, gp)
    return d[:n], jnp.sum(dwp[:, 0], axis=0).astype(w.dtype)


def fused_residual_rms_norm_bwd_twin(res, w, rstd, dres, dnormed, *,
                                     eps=1e-6, rows=None):
    n, h = res.shape
    br = _rows_for(n, rows)
    rp = _pad_rows(res, br)
    sp = jnp.pad(rstd, ((0, rp.shape[0] - n), (0, 0)))
    drp, gp = _pad_rows(dres, br), _pad_rows(dnormed, br)
    jfn = jax.jit(functools.partial(_rrms_bwd_block, eps=eps))
    ds, dws = [], []
    for i in range(rp.shape[0] // br):
        sl = slice(i * br, (i + 1) * br)
        d, dw_p = jfn(rp[sl], w[None, :], sp[sl], drp[sl], gp[sl])
        ds.append(d)
        dws.append(dw_p)
    return (jnp.concatenate(ds, axis=0)[:n],
            jnp.sum(jnp.stack(dws), axis=0).astype(w.dtype))


# --------------------------------------------------------------------------
# differentiable public entries (custom_vjp; [..., h] inputs)
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _rln2d(x, y, w, b, eps, rows, interpret):
    r, o, _, _ = fused_residual_layer_norm_fwd(
        x, y, w, b, eps=eps, rows=rows, interpret=interpret)
    return r, o


def _rln2d_fwd(x, y, w, b, eps, rows, interpret):
    r, o, mean, rstd = fused_residual_layer_norm_fwd(
        x, y, w, b, eps=eps, rows=rows, interpret=interpret)
    return (r, o), (r, w, mean, rstd)


def _rln2d_bwd(eps, rows, interpret, saved, ct):
    r, w, mean, rstd = saved
    dres, dnormed = ct
    d, dw, db = fused_residual_layer_norm_bwd(
        r, w, mean, rstd, dres, dnormed, eps=eps, rows=rows,
        interpret=interpret)
    return d, d, dw, db


_rln2d.defvjp(_rln2d_fwd, _rln2d_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _rrms2d(x, y, w, eps, rows, interpret):
    r, o, _ = fused_residual_rms_norm_fwd(
        x, y, w, eps=eps, rows=rows, interpret=interpret)
    return r, o


def _rrms2d_fwd(x, y, w, eps, rows, interpret):
    r, o, rstd = fused_residual_rms_norm_fwd(
        x, y, w, eps=eps, rows=rows, interpret=interpret)
    return (r, o), (r, w, rstd)


def _rrms2d_bwd(eps, rows, interpret, saved, ct):
    r, w, rstd = saved
    dres, dnormed = ct
    d, dw = fused_residual_rms_norm_bwd(
        r, w, rstd, dres, dnormed, eps=eps, rows=rows,
        interpret=interpret)
    return d, d, dw


_rrms2d.defvjp(_rrms2d_fwd, _rrms2d_bwd)


def fused_residual_layer_norm(x, y, weight, bias, *, eps=1e-5,
                              rows=None, interpret=None):
    """Fused residual+LayerNorm over the last axis: x, y [..., h] ->
    (res, normed) with res = x + y (the blocks' residual-stream value)
    and normed = LN(res) * weight + bias.  Differentiable (custom_vjp,
    fused backward kernel)."""
    shape = x.shape
    r, o = _rln2d(x.reshape(-1, shape[-1]), y.reshape(-1, shape[-1]),
                  weight, bias, float(eps),
                  None if rows is None else int(rows),
                  _resolve_interpret(interpret))
    return r.reshape(shape), o.reshape(shape)


def fused_residual_layer_norm_twin(x, y, weight, bias, *, eps=1e-5,
                                   rows=None):
    shape = x.shape
    r, o, _, _ = fused_residual_layer_norm_fwd_twin(
        x.reshape(-1, shape[-1]), y.reshape(-1, shape[-1]), weight,
        bias, eps=float(eps), rows=rows)
    return r.reshape(shape), o.reshape(shape)


def fused_residual_rms_norm(x, y, weight, *, eps=1e-6, rows=None,
                            interpret=None):
    """Fused residual+RMSNorm over the last axis: (res, normed)."""
    shape = x.shape
    r, o = _rrms2d(x.reshape(-1, shape[-1]), y.reshape(-1, shape[-1]),
                   weight, float(eps),
                   None if rows is None else int(rows),
                   _resolve_interpret(interpret))
    return r.reshape(shape), o.reshape(shape)


def fused_residual_rms_norm_twin(x, y, weight, *, eps=1e-6, rows=None):
    shape = x.shape
    r, o, _ = fused_residual_rms_norm_fwd_twin(
        x.reshape(-1, shape[-1]), y.reshape(-1, shape[-1]), weight,
        eps=float(eps), rows=rows)
    return r.reshape(shape), o.reshape(shape)


# --------------------------------------------------------------------------
# autotune entry: fused_residual_norm_rows
# --------------------------------------------------------------------------
def _row_candidates(rows, hidden):
    """Power-of-two row blocks VMEM-capped on the live tiles (x, y, res,
    normed + the fp32 shadows: ~6 f32 row tiles of width hidden)."""
    cap = 12 * 2 ** 20  # conservative VMEM budget
    cands = []
    for c in (64, 128, 256, 512, 1024):
        if c > max(rows, 64):
            break
        if 6 * c * hidden * 4 > cap:
            break
        cands.append(c)
    return cands or [default_rows(rows)]


def pick_glue_rows(rows, hidden):
    """Row block for the glue kernels through the autotune cache (entry
    ``fused_residual_norm_rows``); sweeps fwd+bwd of the LN variant on
    the real [rows, hidden] geometry (pick_mlp_rows discipline)."""
    import numpy as np

    from . import autotune as at
    cands = _row_candidates(rows, hidden)
    fallback = default_rows(rows)
    if len(cands) <= 1:
        return fallback
    sig = f"r{rows}_h{hidden}"
    try:
        cached = at._load_cache().get(
            f"{at._device_kind()}|fused_residual_norm_rows|{sig}")
    except Exception:
        cached = None
    if cached is not None and cached in cands:
        return int(cached)
    if not at.enabled():
        return fallback

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(rows, hidden)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(rows, hidden)), jnp.float32)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def run(cand):
        def fwd_bwd(xv, yv, wv, bv):
            r, o = _rln2d(xv, yv, wv, bv, 1e-5, int(cand), False)
            return jnp.sum(r * r) + jnp.sum(o * o)

        out = jax.grad(fwd_bwd, argnums=(0, 1, 2, 3))(x, y, w, b)
        jax.block_until_ready(out)

    try:
        return int(at.autotune("fused_residual_norm_rows", sig, cands,
                               run))
    except Exception:
        return fallback

"""TPU Pallas fused-kernel library.

Capability analog of the reference's hand-written CUDA fusion tier
(SURVEY C12/C13: ``paddle/phi/kernels/fusion/gpu/`` and the FlashAttention-2
integration ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91``) — but
implemented as Mosaic/Pallas TPU kernels: online-softmax flash attention
tiled for the MXU, fused norm kernels that keep stats in VMEM, and a fused
rotary-embedding kernel.

Off-TPU (CPU CI, the 8-device virtual mesh) every kernel transparently runs
in Pallas interpreter mode, so the exact same code path is testable without
hardware — the analog of the reference's fake_cpu_device plugin fixture
(SURVEY §4).
"""
from __future__ import annotations

import jax


def use_interpret() -> bool:
    """Pallas kernels compile only for real TPUs; elsewhere interpret."""
    return jax.default_backend() != "tpu"


from . import flash_attention  # noqa: E402
from . import fused_decode_mlp  # noqa: E402
from . import fused_decode_qkv  # noqa: E402
from . import fused_optimizer  # noqa: E402
from . import fused_residual_norm  # noqa: E402
from . import norms  # noqa: E402
from . import rope  # noqa: E402

__all__ = ["flash_attention", "fused_decode_mlp", "fused_decode_qkv",
           "fused_optimizer", "fused_residual_norm", "norms", "rope",
           "use_interpret"]

"""Fused decode-egress Pallas kernels (ISSUE 18 tentpole, kernel 2/2):

* ``fused_decode_mlp`` — attention out-projection + residual + MLP
  (fc1/gelu/fc2 for GPT, gate/up/SwiGLU/down for LLaMA) + second
  residual in ONE dispatch per decode layer;
* ``fused_decode_mlp_partial`` — the tensor-parallel shard-local
  partial of the same chain: norm -> fc1(+act) -> @w2_local, returned
  PRE-psum so the TP decode bodies keep their psum-per-layer contract
  (psum + bias + residual stay outside, exactly where the unfused body
  puts them);
* ``fused_decode_epilogue`` — the final-norm + lm_head + guarded
  greedy argmax sampling step riding the last layer's output tile,
  replaying ``generation.guarded_argmax``'s poison/finiteness math so
  the engine's freeze rule sees bit-identical (next-token, bad) pairs.

Same discipline as fused_decode_qkv: the block math replays the EXACT
unfused op order (functional jnp norms, ``jnp.matmul`` projections,
``jax.nn.gelu(approximate=True)`` / ``jax.nn.silu`` activations,
residual operand order), each kernel has an unjitted jnp twin walking
identical row blocks for BITWISE interpret parity, and the row block is
an autotune entry (``fused_decode_mlp_rows`` — ``pick_mlp_rows``).

Weights are VMEM-resident per block (decode-sized hidden/vocab widths;
the candidates in ``pick_mlp_rows`` are VMEM-capped like the qkv
kernel's).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused_decode_qkv import _norm_block, _row_candidates, \
    default_rows


def _mlp_tail(h, w1, b1, w2, b2, wu, arch):
    """fc1 -> activation -> fc2 (GPT) or gate/up -> SwiGLU -> down
    (LLaMA), matching GPTMLP/LlamaMLP op order."""
    if arch == "gpt":
        f = jnp.matmul(h, w1)
        if b1 is not None:
            f = f + b1
        f = jax.nn.gelu(f, approximate=True)
    else:
        f = jax.nn.silu(jnp.matmul(h, w1)) * jnp.matmul(h, wu)
    f2 = jnp.matmul(f, w2)
    if b2 is not None:
        f2 = f2 + b2
    return f2


def _mlp_block(xv, av, wo, bo, nw, nb, w1, b1, w2, b2, wu, *, arch,
               norm, eps):
    """One row-block of the fused egress math.  Residual operand order
    matches the decode bodies (``x = x + proj(att)`` then
    ``x = x + mlp(norm(x))``)."""
    prj = jnp.matmul(av, wo)
    if bo is not None:
        prj = prj + bo
    y1 = xv + prj
    h = _norm_block(y1, nw, nb, norm, eps)
    return y1 + _mlp_tail(h, w1, b1, w2, b2, wu, arch)


def _mlp_partial_block(yv, nw, nb, w1, b1, w2, wu, *, arch, norm, eps):
    """Shard-local TP partial: norm -> fc1(+act) -> @w2_local, before
    the layer's psum (the TP body adds psum + fc2 bias + residual)."""
    h = _norm_block(yv, nw, nb, norm, eps)
    return _mlp_tail(h, w1, b1, w2, None, wu, arch)


def _epilogue_block(xv, nw, nb, wlm, blm, poisonv, *, norm, eps,
                    transpose_lm):
    """Final norm + lm_head + generation.guarded_argmax math.  Returns
    (logits [rows, V] pre-poison — what the unfused step emits —
    nxt [rows] i32, bad [rows] bool)."""
    h = _norm_block(xv, nw, nb, norm, eps)
    if transpose_lm:
        lg0 = jnp.matmul(h, jnp.swapaxes(wlm, -1, -2))
    else:
        lg0 = jnp.matmul(h, wlm)
        if blm is not None:
            lg0 = lg0 + blm
    lg = lg0.astype(jnp.float32) + poisonv
    bad = ~jnp.all(jnp.isfinite(lg), axis=-1)
    nxt = jnp.where(bad, 0, lg.argmax(axis=-1)).astype(jnp.int32)
    return lg0, nxt, bad


def _pad_rows(x, bp):
    pad = bp - x.shape[0]
    return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1)) \
        if pad else x


def _blocked_call(block_fn, row_args, full_args, n_valid, rows,
                  interpret, n_multi=1):
    """Run ``block_fn(*row_blocks, *full_args)`` over row blocks as ONE
    pallas_call (kernel path) — shared by the three egress wrappers.
    ``row_args`` are [B, ...] tensors blocked on rows; ``full_args`` are
    block-invariant (weights, [1, H] params), with None entries elided
    from the call and re-inserted inside the kernel.  Returns outputs
    sliced back to ``n_valid`` rows."""
    rows_c = n_valid if rows is None else int(rows)
    bp = ((n_valid + rows_c - 1) // rows_c) * rows_c
    row_p = [_pad_rows(a, bp) for a in row_args]
    present = [a for a in full_args if a is not None]
    mask = [a is not None for a in full_args]

    abs_outs = jax.eval_shape(
        block_fn,
        *[jax.ShapeDtypeStruct((rows_c,) + a.shape[1:], a.dtype)
          for a in row_p],
        *[None if a is None else
          jax.ShapeDtypeStruct(a.shape, a.dtype) for a in full_args])
    if not isinstance(abs_outs, tuple):
        abs_outs = (abs_outs,)

    def kernel(*refs):
        vals = iter(refs[:len(row_p) + len(present)])
        rvals = [next(vals)[...] for _ in row_p]
        fvals = [next(vals)[...] if m else None for m in mask]
        outs = block_fn(*rvals, *fvals)
        if not isinstance(outs, tuple):
            outs = (outs,)
        for o_ref, o in zip(refs[len(row_p) + len(present):], outs):
            if o.dtype == jnp.bool_:
                o = o.astype(jnp.int32)  # bool pallas outputs are flaky
            o_ref[...] = o.reshape(o_ref.shape)

    def blk(shape):
        ix = lambda i: (i,) + (0,) * (len(shape) - 1)  # noqa: E731
        return pl.BlockSpec((rows_c,) + tuple(shape[1:]), ix)

    def fullspec(shape):
        return pl.BlockSpec(tuple(shape),
                            lambda i, _n=len(shape): (0,) * _n)

    out_shape, out_specs = [], []
    for o in abs_outs:
        dt = jnp.int32 if o.dtype == jnp.bool_ else o.dtype
        shp = (bp,) + o.shape[1:]
        if len(shp) == 1:
            shp = (bp, 1)
        out_shape.append(jax.ShapeDtypeStruct(shp, dt))
        out_specs.append(blk(shp))

    outs = pl.pallas_call(
        kernel, grid=(bp // rows_c,),
        in_specs=[blk(a.shape) for a in row_p] +
                 [fullspec(a.shape) for a in present],
        out_specs=out_specs, out_shape=out_shape,
        interpret=bool(interpret))(*row_p, *present)
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    final = []
    for o, a in zip(outs, abs_outs):
        o = o[:n_valid]
        if len(a.shape) == 1:
            o = o[:, 0]
        if a.dtype == jnp.bool_:
            o = o != 0
        final.append(o)
    return tuple(final)


def _blocked_twin(block_fn, row_args, full_args, n_valid, rows):
    """Twin of ``_blocked_call`` outside any pallas_call: identical
    padding, identical per-block math, concatenated back — bitwise vs
    interpret mode.  The block math runs under ``jax.jit`` so both
    sides share XLA's elementwise-fusion (FMA) semantics (op-by-op
    eager drifts ~1 ulp on scale/shift chains)."""
    rows_c = n_valid if rows is None else int(rows)
    bp = ((n_valid + rows_c - 1) // rows_c) * rows_c
    row_p = [_pad_rows(a, bp) for a in row_args]
    jfn = jax.jit(block_fn)
    blocks = []
    for i in range(bp // rows_c):
        sl = slice(i * rows_c, (i + 1) * rows_c)
        outs = jfn(*[a[sl] for a in row_p], *full_args)
        blocks.append(outs if isinstance(outs, tuple) else (outs,))
    final = []
    for parts in zip(*blocks):
        final.append(jnp.concatenate(parts, axis=0)[:n_valid])
    return tuple(final)


def _resolve_interpret(interpret):
    if interpret is None:
        from . import use_interpret
        return use_interpret()
    return bool(interpret)


def fused_decode_mlp(x, att, wo, bo, norm_w, norm_b, w1, b1, w2, b2,
                     w_up=None, *, arch="gpt", norm="layer", eps=1e-5,
                     rows=None, interpret=None):
    """x [B, H] residual stream, att [B, nh*hd] attention output ->
    [B, H] after out-proj + residual + MLP + residual."""
    fn = functools.partial(_mlp_block, arch=arch, norm=norm, eps=eps)
    nw = norm_w.reshape(1, -1)
    nb = norm_b.reshape(1, -1) if norm_b is not None else None
    full = [wo, None if bo is None else bo.reshape(1, -1), nw, nb,
            w1, None if b1 is None else b1.reshape(1, -1),
            w2, None if b2 is None else b2.reshape(1, -1), w_up]
    return _blocked_call(lambda xv, av, *f: fn(xv, av, *f),
                         [x, att], full, x.shape[0], rows,
                         _resolve_interpret(interpret))[0]


def fused_decode_mlp_twin(x, att, wo, bo, norm_w, norm_b, w1, b1, w2,
                          b2, w_up=None, *, arch="gpt", norm="layer",
                          eps=1e-5, rows=None, interpret=None):
    del interpret
    fn = functools.partial(_mlp_block, arch=arch, norm=norm, eps=eps)
    nw = norm_w.reshape(1, -1)
    nb = norm_b.reshape(1, -1) if norm_b is not None else None
    full = [wo, None if bo is None else bo.reshape(1, -1), nw, nb,
            w1, None if b1 is None else b1.reshape(1, -1),
            w2, None if b2 is None else b2.reshape(1, -1), w_up]
    return _blocked_twin(lambda xv, av, *f: fn(xv, av, *f),
                         [x, att], full, x.shape[0], rows)[0]


def fused_decode_mlp_partial(y1, norm_w, norm_b, w1, b1, w2, w_up=None,
                             *, arch="gpt", norm="layer", eps=1e-5,
                             rows=None, interpret=None):
    """TP shard-local partial: y1 [B, H] (post-attention residual) ->
    pre-psum MLP partial [B, H]."""
    fn = functools.partial(_mlp_partial_block, arch=arch, norm=norm,
                           eps=eps)
    full = [norm_w.reshape(1, -1),
            None if norm_b is None else norm_b.reshape(1, -1),
            w1, None if b1 is None else b1.reshape(1, -1), w2, w_up]
    return _blocked_call(lambda yv, *f: fn(yv, *f), [y1], full,
                         y1.shape[0], rows,
                         _resolve_interpret(interpret))[0]


def fused_decode_mlp_partial_twin(y1, norm_w, norm_b, w1, b1, w2,
                                  w_up=None, *, arch="gpt",
                                  norm="layer", eps=1e-5, rows=None,
                                  interpret=None):
    del interpret
    fn = functools.partial(_mlp_partial_block, arch=arch, norm=norm,
                           eps=eps)
    full = [norm_w.reshape(1, -1),
            None if norm_b is None else norm_b.reshape(1, -1),
            w1, None if b1 is None else b1.reshape(1, -1), w2, w_up]
    return _blocked_twin(lambda yv, *f: fn(yv, *f), [y1], full,
                         y1.shape[0], rows)[0]


def fused_decode_epilogue(x, norm_w, norm_b, w_lm, b_lm, poison, *,
                          norm="layer", eps=1e-5, transpose_lm=False,
                          rows=None, interpret=None):
    """x [B, H] final hidden state, poison [B] f32 (the engine guard's
    per-slot poison lane) -> (logits [B, V], nxt [B] i32, bad [B]
    bool), with nxt/bad exactly ``guarded_argmax``'s outputs.
    ``transpose_lm`` selects the tied-embedding ``matmul(h, wte.T)``
    form (w_lm passed [V, H])."""
    fn = functools.partial(_epilogue_block, norm=norm, eps=eps,
                           transpose_lm=transpose_lm)
    full = [norm_w.reshape(1, -1),
            None if norm_b is None else norm_b.reshape(1, -1),
            w_lm, None if b_lm is None else b_lm.reshape(1, -1)]
    lg, nxt, bad = _blocked_call(
        lambda xv, pv, *f: fn(xv, *f, pv), [x, poison.reshape(-1, 1)],
        full, x.shape[0], rows, _resolve_interpret(interpret))
    return lg, nxt, bad


def fused_decode_epilogue_twin(x, norm_w, norm_b, w_lm, b_lm, poison,
                               *, norm="layer", eps=1e-5,
                               transpose_lm=False, rows=None,
                               interpret=None):
    del interpret
    fn = functools.partial(_epilogue_block, norm=norm, eps=eps,
                          transpose_lm=transpose_lm)
    full = [norm_w.reshape(1, -1),
            None if norm_b is None else norm_b.reshape(1, -1),
            w_lm, None if b_lm is None else b_lm.reshape(1, -1)]
    lg, nxt, bad = _blocked_twin(
        lambda xv, pv, *f: fn(xv, *f, pv), [x, poison.reshape(-1, 1)],
        full, x.shape[0], rows)
    return lg, nxt, bad


# --------------------------------------------------------------------------
# autotune entry: fused_decode_mlp_rows
# --------------------------------------------------------------------------
def pick_mlp_rows(b, hidden, inter):
    """Row block for fused_decode_mlp through the autotune cache
    (entry ``fused_decode_mlp_rows``); candidates VMEM-capped on the
    widest activation tile (the fc1/gate output)."""
    import numpy as np
    from . import autotune as at
    cands = _row_candidates(b, hidden, inter)
    fallback = default_rows(b)
    if len(cands) <= 1:
        return fallback
    sig = f"b{b}_h{hidden}_i{inter}"
    try:
        cached = at._load_cache().get(
            f"{at._device_kind()}|fused_decode_mlp_rows|{sig}")
    except Exception:
        cached = None
    if cached is not None and cached in cands:
        return int(cached)
    if not at.enabled():
        return fallback

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    att = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    wo = jnp.asarray(rng.normal(size=(hidden, hidden)) * 0.02,
                     jnp.float32)
    nw = jnp.ones((hidden,), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(hidden, inter)) * 0.02,
                     jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(inter, hidden)) * 0.02,
                     jnp.float32)

    def run(cand):
        out = fused_decode_mlp(
            x, att, wo, None, nw, None, w1, None, w2, None,
            arch="llama", norm="rms", eps=1e-6, w_up=w1,
            rows=int(cand))
        jax.block_until_ready(out)

    try:
        return int(at.autotune("fused_decode_mlp_rows", sig, cands,
                               run))
    except Exception:
        return fallback

"""Fused rotary position embedding (RoPE) Pallas kernel.

Capability analog of the reference fused-rope CUDA kernel
(``paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu``, python surface
``paddle.incubate.nn.functional.fused_rotary_position_embedding``): applies
cos/sin rotation to q (and optionally k, v) in one pass, half-rotate
("neox") or interleaved pairing, without materializing the rotated halves
in HBM. RoPE is a linear map whose transpose is the rotation by -theta, so
the backward reuses the same kernel with negated sin.

The interleaved pairing is computed with lane rolls + a parity mask (a
minor-dim reshape/stack does not lower through Mosaic).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, use_neox):
    x = x_ref[0, 0].astype(jnp.float32)        # [S, D]
    cos = cos_ref[0].astype(jnp.float32)       # [S, D]
    sin = sin_ref[0].astype(jnp.float32)
    d = x.shape[-1]
    if use_neox:
        # pair (i, i + d/2): rotate_half
        x1 = x[:, : d // 2]
        x2 = x[:, d // 2:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        # pair (2i, 2i+1): rot[2i] = -x[2i+1], rot[2i+1] = x[2i]
        nxt = pltpu.roll(x, d - 1, 1)          # nxt[i] = x[i+1]
        prv = pltpu.roll(x, 1, 1)              # prv[i] = x[i-1]
        even = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) % 2 == 0
        rot = jnp.where(even, -nxt, prv)
    o_ref[0, 0] = (x * cos + rot * sin).astype(o_ref.dtype)


def _rope_call(x, cos, sin, use_neox, interpret):
    """x: [B, H, S, D]; cos/sin: [S, D] or [B, S, D] (per-batch tables,
    e.g. gathered by position_ids) -> same-shape rotated x."""
    b, h, s, d = x.shape
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    batched = cos.shape[0] != 1
    tab_ix = (lambda ib, ih: (ib, 0, 0)) if batched \
        else (lambda ib, ih: (0, 0, 0))
    kernel = functools.partial(_rope_kernel, use_neox=use_neox)
    return pl.pallas_call(
        kernel,
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda ib, ih: (ib, ih, 0, 0)),
            pl.BlockSpec((1, s, d), tab_ix),
            pl.BlockSpec((1, s, d), tab_ix),
        ],
        out_specs=pl.BlockSpec((1, 1, s, d), lambda ib, ih: (ib, ih, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, cos, sin)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rope_bhsd(x, cos, sin, use_neox, interpret):
    return _rope_call(x, cos, sin, use_neox, interpret)


def _rope_fwd(x, cos, sin, use_neox, interpret):
    return _rope_call(x, cos, sin, use_neox, interpret), (cos, sin)


def _rope_bwd(use_neox, interpret, res, g):
    cos, sin = res
    # transpose of rotation(theta) = rotation(-theta)
    return _rope_call(g, cos, -sin, use_neox, interpret), None, None


_rope_bhsd.defvjp(_rope_fwd, _rope_bwd)


def apply_rope(x, cos, sin, use_neox=True, interpret=None):
    """Rotary embedding in paddle layout [batch, seq, num_heads, head_dim].

    cos/sin: [seq, head_dim] — or [batch, seq, head_dim] for per-example
    position tables — tiled to full head_dim (for ``use_neox=True``:
    ``cos[s, i] = cos(s * inv_freq[i % (d/2)])``; for interleaved:
    ``inv_freq[i // 2]``).
    """
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    xt = jnp.swapaxes(x, 1, 2)
    o = _rope_bhsd(xt, cos.astype(jnp.float32), sin.astype(jnp.float32),
                   bool(use_neox), bool(interpret))
    return jnp.swapaxes(o, 1, 2)

"""Fused multi-tensor optimizer update kernel (SGD / Momentum / Adam /
AdamW) over flat dtype-bucketed state.

Capability analog of the reference's fused optimizer CUDA tier
(``paddle/phi/kernels/fused_adam_kernel.cu``, the ``multi_tensor_apply``
family): one kernel applies gradient clip scale + regularizer fold +
moment updates + weight decay + master-weight cast in a single pass over
a flat bucket (``optimizer/flat.py``), instead of O(num_params) little
elementwise chains.

Two interchangeable implementations with identical arithmetic:

- ``jnp`` — the whole update as ONE jitted XLA elementwise chain per
  bucket. This is the default off-TPU (CPU CI) and the bit-exactness
  reference: it performs exactly the per-param path's float ops, element
  for element, so fused-vs-per-param parity is bitwise.
- ``pallas`` — a Mosaic TPU kernel over the bucket's (rows, 128) tiling
  with ``input_output_aliases`` donating params/master/moments in place
  (the reference's inplace-address-reuse story at kernel granularity).
  Scalars (lr, clip scale, beta powers) ride in SMEM. Row-block size is
  an autotune entry (``fused_optimizer_rows``; heuristic: the largest
  power-of-two divisor of the row count, capped at 512).

Beta powers are per-bucket 0-d scalars (every member of a bucket steps
together, so the per-param beta-pow arrays of the eager path collapse to
one value) and are advanced OUTSIDE the kernel — two scalar ops.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class UpdateSpec:
    """Static (trace-time) configuration of one bucket's fused update."""

    kind: str                 # "sgd" | "momentum" | "adam" | "adamw"
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    momentum: float = 0.9
    nesterov: bool = False
    rescale: float = 1.0
    decay: float = 0.0        # adamw decoupled coefficient
    reg: str | None = None    # "l2" | "l1" | None (grad-folded)
    reg_coeff: float = 0.0
    use_master: bool = False
    has_clip: bool = False    # a clip scale is applied to the grads

    @property
    def has_moment(self):
        return self.kind in ("momentum", "adam", "adamw")

    @property
    def has_adam(self):
        return self.kind in ("adam", "adamw")


def _folded_grad(spec, g, w32, scale):
    """clip scale + f32 cast + regularizer fold, mirroring the per-param
    path's op order bit for bit."""
    if spec.has_clip:
        g = (g.astype(jnp.float32) * scale).astype(g.dtype)
    g32 = g.astype(jnp.float32)
    if spec.reg == "l2" and spec.reg_coeff:
        g32 = g32 + spec.reg_coeff * w32
    elif spec.reg == "l1" and spec.reg_coeff:
        g32 = g32 + spec.reg_coeff * jnp.sign(w32)
    return g32


def _math(spec, lr, scale, w, g, master, m, v, nb1, nb2):
    """The update arithmetic shared by both implementations. ``nb1``/
    ``nb2`` are the ALREADY-advanced beta powers. Returns
    (new_w, new_master, new_m, new_v)."""
    w32 = master if spec.use_master else w.astype(jnp.float32)
    g32 = _folded_grad(spec, g, w32, scale)
    nm = nv = None
    if spec.kind == "sgd":
        new32 = w32 - lr * g32
    elif spec.kind == "momentum":
        if spec.rescale != 1.0:
            g32 = g32 * spec.rescale
        nm = spec.momentum * m + g32
        if spec.nesterov:
            new32 = w32 - lr * (g32 + spec.momentum * nm)
        else:
            new32 = w32 - lr * nm
    else:  # adam / adamw
        if spec.kind == "adamw" and spec.decay:
            w32 = w32 * (1.0 - lr * spec.decay)
        nm = spec.beta1 * m + (1 - spec.beta1) * g32
        nv = spec.beta2 * v + (1 - spec.beta2) * jnp.square(g32)
        m_hat = nm / (1 - nb1)
        v_hat = nv / (1 - nb2)
        new32 = w32 - lr * m_hat / (jnp.sqrt(v_hat) + spec.eps)
    new_w = new32.astype(w.dtype)
    new_master = new32 if spec.use_master else None
    return new_w, new_master, nm, nv


# --------------------------------------------------------------------------
# jnp implementation: the update as one elementwise chain per bucket.
# Deliberately NOT wrapped in jax.jit: under capture it traces inline
# into the step program anyway, and eagerly the op-for-op dispatch keeps
# the arithmetic bitwise identical to the per-param path (a jitted chain
# lets XLA contract mul+add into FMA, which drifts the last ulp — the
# parity suite pins bit-exactness on CPU). Still O(1) ops per bucket.
# --------------------------------------------------------------------------
def _jnp_update(spec, lr, scale, w, g, master, m, v, nb1, nb2):
    return _math(spec, lr, scale, w, g, master, m, v, nb1, nb2)


# --------------------------------------------------------------------------
# Pallas implementation: (rows, 128) tiling, in-place via aliasing
# --------------------------------------------------------------------------
def _kernel(spec, scal_ref, *refs):
    lr = scal_ref[0, 0]
    scale = scal_ref[0, 1]
    nb1 = scal_ref[0, 2]
    nb2 = scal_ref[0, 3]
    it = iter(refs)
    w_ref, g_ref = next(it), next(it)
    m_ref = next(it) if spec.has_moment else None
    v_ref = next(it) if spec.has_adam else None
    mw_ref = next(it) if spec.use_master else None
    ow_ref = next(it)
    om_ref = next(it) if spec.has_moment else None
    ov_ref = next(it) if spec.has_adam else None
    omw_ref = next(it) if spec.use_master else None

    new_w, new_master, nm, nv = _math(
        spec, lr, scale, w_ref[:], g_ref[:],
        mw_ref[:] if mw_ref is not None else None,
        m_ref[:] if m_ref is not None else None,
        v_ref[:] if v_ref is not None else None, nb1, nb2)
    ow_ref[:] = new_w
    if om_ref is not None:
        om_ref[:] = nm
    if ov_ref is not None:
        ov_ref[:] = nv
    if omw_ref is not None:
        omw_ref[:] = new_master


def pick_rows(rows: int, spec: UpdateSpec, dtype) -> int:
    """Row-block size for the kernel grid. Autotune entry
    ``fused_optimizer_rows`` when kernel autotuning is enabled;
    heuristic otherwise (largest power-of-two divisor, capped at 512 —
    ~256 KB of f32 state per step fits VMEM comfortably)."""
    cands = [c for c in (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
             if c <= rows and rows % c == 0]
    if not cands:
        return rows
    heuristic = next((c for c in cands if c <= 512), cands[-1])
    from . import autotune
    if not autotune.enabled() or jax.default_backend() != "tpu":
        return heuristic
    sig = f"r{rows}|{spec.kind}|{jnp.dtype(dtype).name}|mw{spec.use_master}"

    def run(br):
        shape = (rows, 128)
        w = jnp.zeros(shape, dtype)
        g = jnp.ones(shape, dtype)
        m = jnp.zeros(shape, jnp.float32) if spec.has_moment else None
        v = jnp.zeros(shape, jnp.float32) if spec.has_adam else None
        mw = jnp.zeros(shape, jnp.float32) if spec.use_master else None
        outs = _pallas_call(spec, br, False, jnp.float32(1e-3),
                            jnp.float32(1.0), w, g, mw, m, v,
                            jnp.float32(spec.beta1),
                            jnp.float32(spec.beta2))
        jax.block_until_ready(outs)

    return autotune.autotune("fused_optimizer_rows", sig, cands, run)


def _pallas_call(spec, br, interpret, lr, scale, w2, g2, mw2, m2, v2,
                 nb1, nb2):
    from jax.experimental import pallas as pl

    rows = w2.shape[0]
    grid = (rows // br,)
    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(scale, jnp.float32),
                      jnp.asarray(nb1, jnp.float32),
                      jnp.asarray(nb2, jnp.float32)]).reshape(1, 4)

    def blk(dt):
        return pl.BlockSpec((br, 128), lambda i: (i, 0))

    try:
        from jax.experimental.pallas import tpu as pltpu
        scal_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    except Exception:  # interpret mode off-TPU
        scal_spec = pl.BlockSpec((1, 4), lambda i: (0, 0))

    ins = [w2, g2]
    in_specs = [blk(w2.dtype), blk(g2.dtype)]
    outs = [jax.ShapeDtypeStruct(w2.shape, w2.dtype)]
    out_specs = [blk(w2.dtype)]
    # inputs: 0=scal, 1=w, 2=g, then m/v/master; aliases donate in place
    aliases = {1: 0}
    nxt_in, nxt_out = 3, 1
    if spec.has_moment:
        ins.append(m2)
        in_specs.append(blk(m2.dtype))
        outs.append(jax.ShapeDtypeStruct(m2.shape, m2.dtype))
        out_specs.append(blk(m2.dtype))
        aliases[nxt_in] = nxt_out
        nxt_in += 1
        nxt_out += 1
    if spec.has_adam:
        ins.append(v2)
        in_specs.append(blk(v2.dtype))
        outs.append(jax.ShapeDtypeStruct(v2.shape, v2.dtype))
        out_specs.append(blk(v2.dtype))
        aliases[nxt_in] = nxt_out
        nxt_in += 1
        nxt_out += 1
    if spec.use_master:
        ins.append(mw2)
        in_specs.append(blk(mw2.dtype))
        outs.append(jax.ShapeDtypeStruct(mw2.shape, mw2.dtype))
        out_specs.append(blk(mw2.dtype))
        aliases[nxt_in] = nxt_out

    return pl.pallas_call(
        functools.partial(_kernel, spec),
        grid=grid,
        in_specs=[scal_spec] + in_specs,
        out_specs=out_specs,
        out_shape=outs,
        input_output_aliases=aliases,
        interpret=interpret,
    )(scal, *ins)


def _pallas_update(spec, lr, scale, w, g, master, m, v, nb1, nb2,
                   interpret):
    n = w.shape[0]
    rows = n // 128
    shape2 = (rows, 128)
    br = pick_rows(rows, spec, w.dtype)
    res = _pallas_call(
        spec, br, interpret, lr, scale, w.reshape(shape2),
        g.reshape(shape2),
        master.reshape(shape2) if master is not None else None,
        m.reshape(shape2) if m is not None else None,
        v.reshape(shape2) if v is not None else None, nb1, nb2)
    it = iter(res)
    new_w = next(it).reshape(n)
    nm = next(it).reshape(n) if spec.has_moment else None
    nv = next(it).reshape(n) if spec.has_adam else None
    new_master = next(it).reshape(n) if spec.use_master else None
    return new_w, new_master, nm, nv


# --------------------------------------------------------------------------
# entry
# --------------------------------------------------------------------------
def fused_update(spec: UpdateSpec, *, w, g, lr, clip_scale=None,
                 master=None, m=None, v=None, b1p=None, b2p=None,
                 impl=None):
    """One fused update over a flat bucket.

    All array args are 1-D flats of equal (ALIGN-padded) length; ``lr``
    and ``clip_scale`` are f32 scalars (traced or concrete); ``b1p``/
    ``b2p`` are the bucket's CURRENT beta powers (advanced here).
    Returns ``(new_w, new_master, new_m, new_v, new_b1p, new_b2p)`` with
    ``None`` for absent slots. ``impl``: None (auto: pallas on TPU, jnp
    elsewhere) | "jnp" | "pallas" | "pallas_interpret".
    """
    lr = jnp.asarray(lr, jnp.float32)
    scale = (jnp.asarray(clip_scale, jnp.float32)
             if clip_scale is not None else jnp.float32(1.0))
    nb1 = b1p * spec.beta1 if spec.has_adam else jnp.float32(1.0)
    nb2 = b2p * spec.beta2 if spec.has_adam else jnp.float32(1.0)
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl == "jnp":
        new_w, new_master, nm, nv = _jnp_update(
            spec, lr, scale, w, g, master, m, v, nb1, nb2)
    else:
        new_w, new_master, nm, nv = _pallas_update(
            spec, lr, scale, w, g, master, m, v, nb1, nb2,
            interpret=(impl == "pallas_interpret"))
    return (new_w, new_master, nm, nv,
            nb1 if spec.has_adam else None,
            nb2 if spec.has_adam else None)

"""Pallas TPU flash attention (forward + backward, causal + GQA).

Capability analog of the reference FlashAttention-2 integration
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91`` fwd,
``flash_attn_grad_kernel.cu`` bwd, python surface
``python/paddle/nn/functional/flash_attention.py:147``) — TPU-native design:

* online-softmax tiling sized for the MXU (q blocks x k blocks, fp32
  accumulators in registers/VMEM, bf16 matmul inputs);
* per-(batch, head) grid programs keep K/V resident in VMEM while a q block
  streams through — no [S, S] score matrix ever exists in HBM;
* causal programs stop the k loop at the diagonal block (the FA2 trick that
  halves causal FLOPs);
* grouped-query attention maps q-head -> kv-head in the BlockSpec index map
  (no materialized ``repeat`` of K/V, unlike the XLA fallback);
* backward recomputes the softmax from the saved logsumexp (flash-attn
  recompute strategy): a dk/dv pass tiled over k blocks and a dq pass tiled
  over q blocks.

Public entry: ``flash_attention(q, k, v, causal=..., scale=...)`` in
paddle's [batch, seq, num_heads, head_dim] layout, differentiable via
``jax.custom_vjp``.
"""
from __future__ import annotations

import functools
import math
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free
_LANE = 8  # trailing lane width for per-row stats (Mosaic tile alignment)


def _block_sizes(sq, sk):
    """Default (block_q, block_k). Measured on the v5e-class chip with the
    dispatch-free scan-slope method (benchmarks/attn_sweep.py): 512x512 is
    3-8x faster than 128x128 at b8/h12/s1024/d64 (fwd 0.41 ms vs 1.46 ms;
    grad call 0.36-1.2 ms vs 2.96 ms) — bigger q/k tiles amortize the
    per-block softmax/stat work over more MXU cycles. VMEM stays
    comfortable: K/V are already held full-length per (batch, head)
    program."""
    bq = min(512, sq)
    bk = min(512, sk)
    return bq, bk


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, has_seg,
                sq, sk, bq, bk):
    """One (batch, q-head, q-block) program: stream k/v blocks with online
    softmax. Block shapes: q/o [1,1,bq,D]; k/v [1,1,Skp,D]; lse
    [1,1,bq,LANE] (Mosaic needs the trailing dims tile-aligned, so the
    per-row logsumexp is replicated across a small lane axis). With
    ``has_seg``, per-token segment ids (q [1,bq], kv [1,Skp]) confine
    attention to same-segment pairs (varlen/packed-sequence support —
    the reference's ``flash_attn_varlen_fwd`` capability)."""
    if has_seg:
        qs_ref, ks_ref, o_ref, lse_ref = refs
    else:
        o_ref, lse_ref = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale       # [bq, D]
    offset = sk - sq                                   # causal diagonal shift

    nk = pl.cdiv(sk, bk)
    if causal:
        # last k block that the last row of this q block can see
        hi = jnp.minimum(nk, ((iq + 1) * bq + offset + bk - 1) // bk)
    else:
        hi = nk

    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
    cols0 = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def body(j, carry):
        m_i, l_i, acc = carry
        kb = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        cols = cols0 + j * bk
        mask = cols < sk                               # k padding
        if causal:
            mask = mask & (rows + offset >= cols)
        if has_seg:
            qs = qs_ref[0]                             # [bq]
            ks = ks_ref[0, pl.ds(j * bk, bk)]          # [bk]
            mask = mask & (qs[:, None] == ks[None, :])
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                         # [bq, bk]
        alpha = jnp.exp(m_i - m_new)                   # [bq, 1]
        l_new = l_i * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
    m_f, l_f, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, a0))

    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)           # padded q rows
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m_f + jnp.log(l_safe), (bq, _LANE))


def _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks=None):
    """q [B,Hq,Sq,D]; k,v [B,Hk,Sk,D]; seg_q/seg_k optional [B,Sq]/[B,Sk]
    int32 segment ids -> (o [B,Hq,Sq,D], lse [B,Hq,Sq])."""
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    rep = hq // hk
    has_seg = seg_q is not None
    bq, bk = blocks if blocks is not None else _block_sizes(sq, sk)
    bq, bk = min(bq, sq), min(bk, sk)
    qp = _pad_to(q, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    sqp, skp = qp.shape[2], kp.shape[2]
    grid = (b, hq, sqp // bq)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, sq=sq, sk=sk, bq=bq, bk=bk)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
        pl.BlockSpec((1, 1, skp, d),
                     lambda ib, ih, iq, _rep=rep: (ib, ih // _rep, 0, 0)),
        pl.BlockSpec((1, 1, skp, d),
                     lambda ib, ih, iq, _rep=rep: (ib, ih // _rep, 0, 0)),
    ]
    args = [qp, kp, vp]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)),
            pl.BlockSpec((1, skp), lambda ib, ih, iq: (ib, 0)),
        ]
        args += [_pad_to(seg_q.astype(jnp.int32), 1, bq),
                 _pad_to(seg_k.astype(jnp.int32), 1, bk)]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, _LANE),
                         lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sqp, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o[:, :, :sq], lse[:, :, :sq, 0]


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, scale, causal, has_seg, sq, sk, bq, bk):
    """One (batch, q-head, k-block) program: accumulate this k block's
    dk/dv over all attending q blocks. GQA heads are summed by the caller."""
    if has_seg:
        qs_ref, ks_ref, dk_ref, dv_ref = refs
    else:
        dk_ref, dv_ref = refs
        qs_ref = ks_ref = None
    ik = pl.program_id(2)
    kb = k_ref[0, 0].astype(jnp.float32)               # [bk, D]
    vb = v_ref[0, 0].astype(jnp.float32)
    offset = sk - sq

    nq = pl.cdiv(sq, bq)
    if causal:
        lo = jnp.maximum(0, (ik * bk - offset) // bq)  # first attending q
    else:
        lo = 0

    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
    rows0 = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(iq, carry):
        dk, dv = carry
        qb = q_ref[0, 0, pl.ds(iq * bq, bq), :].astype(jnp.float32) * scale
        dob = do_ref[0, 0, pl.ds(iq * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(iq * bq, bq), 0:1]   # [bq, 1]
        dlt = delta_ref[0, 0, pl.ds(iq * bq, bq), 0:1]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        rows = rows0 + iq * bq
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (rows + offset >= cols)
        if has_seg:
            qs = qs_ref[0, pl.ds(iq * bq, bq)]         # [bq]
            ks = ks_ref[0, pl.ds(ik * bk, bk)]         # [bk]
            mask = mask & (qs[:, None] == ks[None, :])
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, D]
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dlt)                            # [bq, bk]
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, D]
        return dk, dv

    z = jnp.zeros((bk, kb.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *refs, scale, causal, has_seg, sq, sk, bq, bk):
    """One (batch, q-head, q-block) program: this q block's dq."""
    if has_seg:
        qs_ref, ks_ref, dq_ref = refs
    else:
        (dq_ref,) = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32) * scale       # [bq, D]
    dob = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0:1]                        # [bq, 1]
    dlt = delta_ref[0, 0, :, 0:1]
    offset = sk - sq

    nk = pl.cdiv(sk, bk)
    if causal:
        hi = jnp.minimum(nk, ((iq + 1) * bq + offset + bk - 1) // bk)
    else:
        hi = nk

    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
    cols0 = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def body(j, dq):
        kb = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        cols = cols0 + j * bk
        mask = cols < sk
        if causal:
            mask = mask & (rows + offset >= cols)
        if has_seg:
            qs = qs_ref[0]                             # [bq]
            ks = ks_ref[0, pl.ds(j * bk, bk)]          # [bk]
            mask = mask & (qs[:, None] == ks[None, :])
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dlt)
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, hi, body, jnp.zeros((bq, qb.shape[-1]), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd(scale, causal, interpret, blocks, res, g):
    q, k, v, seg_q, seg_k, o, lse = res
    do = g
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    rep = hq // hk
    has_seg = seg_q is not None
    bq, bk = blocks if blocks is not None else _block_sizes(sq, sk)
    bq, bk = min(bq, sq), min(bk, sk)
    if has_seg:
        sqp_pad = _pad_to(seg_q.astype(jnp.int32), 1, bq)
        skp_pad = _pad_to(seg_k.astype(jnp.int32), 1, bk)

    # delta_i = rowsum(dO * O): the FA2 precompute — one fused XLA reduce
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp = _pad_to(q, 2, bq)
    dop = _pad_to(do, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    sqp, skp = qp.shape[2], kp.shape[2]
    # per-row stats carried lane-replicated [B, H, Sqp, _LANE] (tiling rule)
    lsep = jnp.broadcast_to(_pad_to(lse, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))
    dltp = jnp.broadcast_to(_pad_to(delta, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))

    # --- dk/dv: grid over k blocks; one output copy per q head, summed
    # over the GQA group afterwards (B*Hq programs write disjoint slices).
    kernel = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, sq=sq, sk=sk, bq=bq, bk=bk)
    kv_spec = pl.BlockSpec(
        (1, 1, bk, d),
        lambda ib, ih, ikb, _rep=rep: (ib, ih // _rep, ikb, 0))
    q_full = pl.BlockSpec((1, 1, sqp, d), lambda ib, ih, ikb: (ib, ih, 0, 0))
    v1_full = pl.BlockSpec((1, 1, sqp, _LANE),
                           lambda ib, ih, ikb: (ib, ih, 0, 0))
    in_specs = [q_full, kv_spec, kv_spec, q_full, v1_full, v1_full]
    args = [qp, kp, vp, dop, lsep, dltp]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, sqp), lambda ib, ih, ikb: (ib, 0)),
            pl.BlockSpec((1, skp), lambda ib, ih, ikb: (ib, 0)),
        ]
        args += [sqp_pad, skp_pad]
    dkh, dvh = pl.pallas_call(
        kernel,
        grid=(b, hq, skp // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ikb: (ib, ih, ikb, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ikb: (ib, ih, ikb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, skp, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    if rep > 1:
        dkh = dkh.reshape(b, hk, rep, skp, d).sum(axis=2)
        dvh = dvh.reshape(b, hk, rep, skp, d).sum(axis=2)
    dk = dkh[:, :, :sk].astype(k.dtype)
    dv = dvh[:, :, :sk].astype(v.dtype)

    # --- dq: grid over q blocks
    kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, sq=sq, sk=sk, bq=bq, bk=bk)
    qb_spec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, skp, d),
                           lambda ib, ih, iq, _rep=rep: (ib, ih // _rep, 0, 0))
    v1_spec = pl.BlockSpec((1, 1, bq, _LANE),
                           lambda ib, ih, iq: (ib, ih, iq, 0))
    in_specs = [qb_spec, kv_spec, kv_spec, qb_spec, v1_spec, v1_spec]
    args = [qp, kp, vp, dop, lsep, dltp]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)),
            pl.BlockSpec((1, skp), lambda ib, ih, iq: (ib, 0)),
        ]
        args += [sqp_pad, skp_pad]
    dq = pl.pallas_call(
        kernel,
        grid=(b, hq, sqp // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, iq: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sqp, d), q.dtype),
        interpret=interpret,
    )(*args)
    return dq[:, :, :sq], dk, dv, None, None


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_bhsd(q, k, v, seg_q, seg_k, scale, causal, interpret,
                blocks=None):
    o, _ = _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks)
    return o


def _flash_fwd_rule(q, k, v, seg_q, seg_k, scale, causal, interpret,
                    blocks=None):
    o, lse = _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks)
    return o, (q, k, v, seg_q, seg_k, o, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


_TUNE_CANDIDATES = ((128, 128), (256, 256), (256, 512), (512, 256),
                    (512, 512), (512, 1024), (1024, 512), (1024, 1024))


def _autotuned_blocks(qt, kt, scale, causal):
    """Block-size selection through the autotune cache (SURVEY C14; see
    autotune.py). Under a trace (tracer inputs) only cache HITS apply —
    the shapes are static so the key is known; the measuring sweep runs
    when inputs are concrete (first eager call, or an explicit warmup
    like bench.py's)."""
    from . import autotune as at
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    cands = [c for c in _TUNE_CANDIDATES if c[0] <= sq and c[1] <= sk]
    if len(cands) <= 1:
        return None
    sig = f"b{b}h{h}sq{sq}sk{sk}d{d}c{int(causal)}"
    key = f"{at._device_kind()}|flash_attention|{sig}"
    cached = at._load_cache().get(key)
    if cached is not None:
        for c in cands:
            if at._same_candidate(c, cached):
                return tuple(c)
    if isinstance(qt, jax.core.Tracer):
        return None  # no timing possible mid-trace; use defaults
    runners = {}

    def _timed(cand, reps):
        # ``reps`` fwd+bwd applications scanned inside ONE jit (the q
        # input is index-perturbed so XLA cannot CSE the iterations; the
        # scan compiles each kernel once regardless of reps). The
        # difference between two rep counts is pure kernel time
        # (scan-slope — constant dispatch/tunnel latency cancels;
        # per-call wall timing over a network-attached chip is
        # jitter-dominated and picks wrong winners). Training is the
        # tuner's consumer, so the BACKWARD kernels are timed too —
        # fwd-only timing picks blocks whose bwd is slow.
        f = runners.get((cand, reps))
        if f is None:
            grad = jax.grad(
                lambda a, bb, cc, _cand=tuple(cand): _flash_bhsd(
                    a, bb, cc, None, None, scale, causal, False,
                    _cand).astype(jnp.float32).sum(),
                argnums=(0, 1, 2))

            def chained(a, bb, cc, _n=reps):
                def body(c, i):
                    # every grad output must feed the carry: an unused
                    # dk/dv would let XLA dead-code-eliminate the dkv
                    # kernel (the dominant backward cost) from the timed
                    # program. dk/dv fold in as scalars so rectangular
                    # attention (sq != sk) stays timeable.
                    dq, dk, dv = grad(a + i.astype(a.dtype) * 1e-6, bb, cc)
                    extra = (dk.sum() + dv.sum()).astype(a.dtype)
                    return c + dq.astype(a.dtype) + extra, None
                z = jnp.zeros(a.shape, a.dtype)
                return jax.lax.scan(body, z, jnp.arange(_n))[0]

            f = runners[(cand, reps)] = jax.jit(chained)
        out = f(qt, kt, kt)
        float(jax.device_get(out.ravel()[0]))  # compile/warm + sync
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(qt, kt, kt)
            float(jax.device_get(out.ravel()[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    def measure(cand):
        r1, r2 = 4, 24
        slope = (_timed(cand, r2) - _timed(cand, r1)) / (r2 - r1)
        if slope <= 0:
            # below timing resolution (dispatch jitter swamped the
            # 20-rep kernel delta): never let noise crown a winner
            return float("inf")
        return slope

    def validate(cand):
        # the measuring jit may fuse/lay out differently than the real
        # call, and the backward kernels have the larger vmem footprint
        # (dk/dv accumulators + the q loop). Compile+run fwd AND bwd in
        # the caller's real eager context — a scoped-vmem overflow in
        # either disqualifies the candidate and the next-best wins.
        def f(a, bb, cc):
            return _flash_bhsd(a, bb, cc, None, None, scale, causal,
                               False, tuple(cand)).astype(jnp.float32).sum()
        grads = jax.grad(f, argnums=(0, 1, 2))(qt, kt, kt)
        float(jax.device_get(grads[0].ravel()[0]))  # force execution

    try:
        return tuple(at.autotune("flash_attention", sig, cands, None,
                                 measure=measure, validate=validate))
    except RuntimeError:
        # every candidate failed or was below timing resolution: fall
        # back to the measured defaults rather than crashing the call
        # (nothing is cached, so a later quieter run can still tune)
        return None


def flash_attention(q, k, v, causal=False, scale=None, interpret=None,
                    blocks=None, segment_ids=None):
    """Flash attention in paddle layout [batch, seq, num_heads, head_dim].

    ``num_heads(q)`` may be a multiple of ``num_heads(k) == num_heads(v)``
    (grouped-query attention). Returns [batch, seq_q, num_heads, head_dim].
    ``blocks``: optional (block_q, block_k) override; with autotuning
    enabled (``incubate.autotune.set_config``) the best pair is measured
    on-device and cached per shape.
    ``segment_ids``: varlen/packed-sequence support (the capability of the
    reference's ``flash_attn_varlen_fwd``,
    ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91``): an int array
    [batch, seq] (shared q/kv when lengths match) or a pair
    ``(q_seg [B,Sq], kv_seg [B,Sk])``; attention is confined to positions
    with equal segment id, composing with ``causal``.
    """
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hk = q.shape[2], k.shape[2]
    if hk == 0 or hq % hk != 0:
        raise ValueError(
            f"flash_attention: query heads ({hq}) must be a multiple of "
            f"key/value heads ({hk}) for grouped-query attention")
    seg_q = seg_k = None
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            seg_q, seg_k = segment_ids
        else:
            if q.shape[1] != k.shape[1]:
                raise ValueError(
                    "flash_attention: a single segment_ids array needs "
                    "seq_q == seq_k; pass (q_seg, kv_seg) otherwise")
            seg_q = seg_k = segment_ids
        seg_q = jnp.asarray(seg_q, jnp.int32)
        seg_k = jnp.asarray(seg_k, jnp.int32)
    qt = jnp.swapaxes(q, 1, 2)  # -> [B, H, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if blocks is None and not interpret and segment_ids is None:
        from . import autotune as at
        if at.enabled():
            blocks = _autotuned_blocks(qt, kt, float(scale), bool(causal))
    o = _flash_bhsd(qt, kt, vt, seg_q, seg_k, float(scale), bool(causal),
                    bool(interpret), blocks)
    return jnp.swapaxes(o, 1, 2)

"""Pallas TPU flash attention (forward + backward, causal + GQA).

Capability analog of the reference FlashAttention-2 integration
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91`` fwd,
``flash_attn_grad_kernel.cu`` bwd, python surface
``python/paddle/nn/functional/flash_attention.py:147``) — TPU-native design:

* online-softmax tiling sized for the MXU (q blocks x k blocks, fp32
  accumulators in registers/VMEM, bf16 matmul inputs);
* per-(batch, head) grid programs keep K/V resident in VMEM while a q block
  streams through — no [S, S] score matrix ever exists in HBM;
* causal programs stop the k loop at the diagonal block (the FA2 trick that
  halves causal FLOPs);
* grouped-query attention maps q-head -> kv-head in the BlockSpec index map
  (no materialized ``repeat`` of K/V, unlike the XLA fallback);
* backward recomputes the softmax from the saved logsumexp (flash-attn
  recompute strategy) in ONE fused kernel: a 4-D grid walks (k-block,
  q-block) tiles, recomputing the attention probabilities ONCE per tile
  and producing dk/dv (VMEM accumulators over the q grid dim) AND dq (a
  persistent full-row VMEM scratch accumulated over the k grid dim) from
  the same ``p``/``ds`` — the previous two-pass backward paid the s/p
  recompute twice (7 tile dots; fused is 5, the ~2.5x-over-forward FLOP
  ideal instead of the measured 4.5x).

Parity discipline (the ``quant_matmul_jnp`` contract):
``flash_attention_bwd_jnp`` is an UNJITTED jnp twin replaying the fused
kernel's exact tile walk — same per-tile dot shapes, same accumulate
order, same masks — so Pallas-interpret backward grads are BITWISE equal
to the twin on CPU for every geometry (causal x GQA x segment-ids x
padded tails). Backward block sizes are tuned separately from the
forward under the ``flash_attention_bwd`` autotune entry (the backward's
VMEM footprint — full-row q/do/dq buffers plus the k-tile accumulators —
admits different winners than the forward).

Public entry: ``flash_attention(q, k, v, causal=..., scale=...)`` in
paddle's [batch, seq, num_heads, head_dim] layout, differentiable via
``jax.custom_vjp``.
"""
from __future__ import annotations

import functools
import math
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free
_LANE = 8  # trailing lane width for per-row stats (Mosaic tile alignment)


def _block_sizes(sq, sk):
    """Default (block_q, block_k). Measured on the v5e-class chip with the
    dispatch-free scan-slope method (benchmarks/attn_sweep.py): 512x512 is
    3-8x faster than 128x128 at b8/h12/s1024/d64 (fwd 0.41 ms vs 1.46 ms;
    grad call 0.36-1.2 ms vs 2.96 ms) — bigger q/k tiles amortize the
    per-block softmax/stat work over more MXU cycles. VMEM stays
    comfortable: K/V are already held full-length per (batch, head)
    program."""
    bq = min(512, sq)
    bk = min(512, sk)
    return bq, bk


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, has_seg,
                sq, sk, bq, bk):
    """One (batch, q-head, q-block) program: stream k/v blocks with online
    softmax. Block shapes: q/o [1,1,bq,D]; k/v [1,1,Skp,D]; lse
    [1,1,bq,LANE] (Mosaic needs the trailing dims tile-aligned, so the
    per-row logsumexp is replicated across a small lane axis). With
    ``has_seg``, per-token segment ids (q [1,bq], kv [1,Skp]) confine
    attention to same-segment pairs (varlen/packed-sequence support —
    the reference's ``flash_attn_varlen_fwd`` capability)."""
    if has_seg:
        qs_ref, ks_ref, o_ref, lse_ref = refs
    else:
        o_ref, lse_ref = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale       # [bq, D]
    offset = sk - sq                                   # causal diagonal shift

    nk = pl.cdiv(sk, bk)
    if causal:
        # last k block that the last row of this q block can see
        hi = jnp.minimum(nk, ((iq + 1) * bq + offset + bk - 1) // bk)
    else:
        hi = nk

    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
    cols0 = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def body(j, carry):
        m_i, l_i, acc = carry
        kb = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        cols = cols0 + j * bk
        mask = cols < sk                               # k padding
        if causal:
            mask = mask & (rows + offset >= cols)
        if has_seg:
            qs = qs_ref[0]                             # [bq]
            ks = ks_ref[0, pl.ds(j * bk, bk)]          # [bk]
            mask = mask & (qs[:, None] == ks[None, :])
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                         # [bq, bk]
        alpha = jnp.exp(m_i - m_new)                   # [bq, 1]
        l_new = l_i * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
    m_f, l_f, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, a0))

    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)           # padded q rows
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m_f + jnp.log(l_safe), (bq, _LANE))


def _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks=None):
    """q [B,Hq,Sq,D]; k,v [B,Hk,Sk,D]; seg_q/seg_k optional [B,Sq]/[B,Sk]
    int32 segment ids -> (o [B,Hq,Sq,D], lse [B,Hq,Sq])."""
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    rep = hq // hk
    has_seg = seg_q is not None
    bq, bk = blocks if blocks is not None else _block_sizes(sq, sk)
    bq, bk = min(bq, sq), min(bk, sk)
    qp = _pad_to(q, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    sqp, skp = qp.shape[2], kp.shape[2]
    grid = (b, hq, sqp // bq)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, sq=sq, sk=sk, bq=bq, bk=bk)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
        pl.BlockSpec((1, 1, skp, d),
                     lambda ib, ih, iq, _rep=rep: (ib, ih // _rep, 0, 0)),
        pl.BlockSpec((1, 1, skp, d),
                     lambda ib, ih, iq, _rep=rep: (ib, ih // _rep, 0, 0)),
    ]
    args = [qp, kp, vp]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)),
            pl.BlockSpec((1, skp), lambda ib, ih, iq: (ib, 0)),
        ]
        args += [_pad_to(seg_q.astype(jnp.int32), 1, bq),
                 _pad_to(seg_k.astype(jnp.int32), 1, bk)]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, _LANE),
                         lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sqp, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o[:, :, :sq], lse[:, :, :sq, 0]


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------
def _bwd_block_sizes(sq, sk):
    """Default backward (block_q, block_k). The fused kernel holds
    full-row q/do/dq buffers regardless of the block pair, so the tile
    choice trades MXU utilization against the dk/dv accumulator + k/v
    tile footprint only; 512x512 matches the measured forward default
    and is re-tuned per shape under the ``flash_attention_bwd`` autotune
    entry."""
    return min(512, sq), min(512, sk)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *refs, scale, causal, has_seg, sq, sk, bq, bk,
                      nq, nk):
    """One (batch, q-head, k-block, q-block) tile of the FUSED backward.

    The grid's two inner dims walk k-blocks (outer) x q-blocks (inner);
    each tile recomputes the attention probabilities ONCE and feeds all
    three gradients from the same ``p``/``ds``:

    - dk/dv accumulate in VMEM scratch over the q dim (re-zeroed at
      ``iq == 0``, flushed to their per-k-block output at
      ``iq == nq - 1`` — the quant_matmul K-grid accumulator pattern);
    - dq accumulates in a PERSISTENT full-row VMEM scratch over the k
      dim (scratch lives across grid steps; each q-row slice is zeroed
      at ``ik == 0`` and flushed to the dq output once its last
      attending k block — ``hi - 1`` — has contributed).

    Causal tiles strictly above the diagonal are predicated off with
    ``pl.when`` (the skip that halves causal backward FLOPs); the
    zero-init/flush bookkeeping runs outside the predicate so padded or
    never-attending rows still produce zeros.
    """
    if has_seg:
        qs_ref, ks_ref, dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc \
            = refs
    else:
        dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc = refs
        qs_ref = ks_ref = None
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    offset = sk - sq

    @pl.when(iq == 0)
    def _zero_kv_acc():
        dk_acc[...] = jnp.zeros(dk_acc.shape, jnp.float32)
        dv_acc[...] = jnp.zeros(dv_acc.shape, jnp.float32)

    @pl.when(ik == 0)
    def _zero_dq_slice():
        dq_acc[pl.ds(iq * bq, bq), :] = jnp.zeros(
            (bq, dq_acc.shape[-1]), jnp.float32)

    if causal:
        lo = jnp.maximum(0, (ik * bk - offset) // bq)  # first attending q
        active = iq >= lo
    else:
        active = None

    def tile():
        kb = k_ref[0, 0].astype(jnp.float32)           # [bk, D]
        vb = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0, pl.ds(iq * bq, bq), :].astype(jnp.float32) * scale
        dob = do_ref[0, 0, pl.ds(iq * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(iq * bq, bq), 0:1]   # [bq, 1]
        dlt = delta_ref[0, 0, pl.ds(iq * bq, bq), 0:1]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (rows + offset >= cols)
        if has_seg:
            qs = qs_ref[0, pl.ds(iq * bq, bq)]         # [bq]
            ks = ks_ref[0, pl.ds(ik * bk, bk)]         # [bk]
            mask = mask & (qs[:, None] == ks[None, :])
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)     # recomputed ONCE
        dv_acc[...] += jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, D]
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dlt)                            # [bq, bk]
        dk_acc[...] += jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, D]
        # accumulate UNSCALED: a fused multiply in the accumulate chain
        # FMA-contracts under compilation and drifts the last ulp vs the
        # unjitted twin; the single scale multiply happens at flush
        dq_acc[pl.ds(iq * bq, bq), :] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(active)(tile)
    else:
        tile()

    # flush dq once this q row's LAST attending k block has run. hi can
    # be <= 0 for rows that attend nothing (sq > sk rectangles): clamp
    # to 1 so the zeroed slice still flushes at ik == 0.
    if causal:
        hi = jnp.minimum(nk, ((iq + 1) * bq + offset + bk - 1) // bk)
        hi = jnp.maximum(hi, 1)
    else:
        hi = nk

    @pl.when(ik == hi - 1)
    def _flush_dq():
        dq_ref[0, 0, pl.ds(iq * bq, bq), :] = \
            (dq_acc[pl.ds(iq * bq, bq), :] * scale).astype(dq_ref.dtype)

    @pl.when(iq == nq - 1)
    def _flush_kv():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(scale, causal, interpret, blocks, bwd_blocks, res, g):
    q, k, v, seg_q, seg_k, o, lse = res
    do = g
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    rep = hq // hk
    has_seg = seg_q is not None
    # precedence: explicit bwd_blocks > the forward's (possibly caller-
    # pinned) pair > the measured default — a caller who pinned blocks=
    # gets the pre-split behavior of one pair driving both directions
    bq, bk = (bwd_blocks if bwd_blocks is not None
              else blocks if blocks is not None
              else _bwd_block_sizes(sq, sk))
    bq, bk = min(bq, sq), min(bk, sk)

    # delta_i = rowsum(dO * O): the FA2 precompute — one fused XLA reduce
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp = _pad_to(q, 2, bq)
    dop = _pad_to(do, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    sqp, skp = qp.shape[2], kp.shape[2]
    nq, nk = sqp // bq, skp // bk
    # per-row stats carried lane-replicated [B, H, Sqp, _LANE] (tiling rule)
    lsep = jnp.broadcast_to(_pad_to(lse, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))
    dltp = jnp.broadcast_to(_pad_to(delta, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))

    # ONE fused kernel; grid (b, hq, k-blocks, q-blocks). dk/dv come out
    # per q head (B*Hq programs write disjoint slices) and are summed
    # over the GQA group afterwards.
    kernel = functools.partial(_bwd_fused_kernel, scale=scale,
                               causal=causal, has_seg=has_seg, sq=sq,
                               sk=sk, bq=bq, bk=bk, nq=nq, nk=nk)
    kv_spec = pl.BlockSpec(
        (1, 1, bk, d),
        lambda ib, ih, ikb, iqb, _rep=rep: (ib, ih // _rep, ikb, 0))
    q_full = pl.BlockSpec((1, 1, sqp, d),
                          lambda ib, ih, ikb, iqb: (ib, ih, 0, 0))
    v1_full = pl.BlockSpec((1, 1, sqp, _LANE),
                           lambda ib, ih, ikb, iqb: (ib, ih, 0, 0))
    in_specs = [q_full, kv_spec, kv_spec, q_full, v1_full, v1_full]
    args = [qp, kp, vp, dop, lsep, dltp]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, sqp), lambda ib, ih, ikb, iqb: (ib, 0)),
            pl.BlockSpec((1, skp), lambda ib, ih, ikb, iqb: (ib, 0)),
        ]
        args += [_pad_to(seg_q.astype(jnp.int32), 1, bq),
                 _pad_to(seg_k.astype(jnp.int32), 1, bk)]
    dqh, dkh, dvh = pl.pallas_call(
        kernel,
        grid=(b, hq, nk, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, sqp, d),
                         lambda ib, ih, ikb, iqb: (ib, ih, 0, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ikb, iqb: (ib, ih, ikb, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ikb, iqb: (ib, ih, ikb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sqp, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, skp, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((sqp, d), jnp.float32),   # dq rows (persistent)
            pltpu.VMEM((bk, d), jnp.float32),    # dk accumulator
            pltpu.VMEM((bk, d), jnp.float32),    # dv accumulator
        ],
        interpret=interpret,
    )(*args)
    if rep > 1:
        dkh = dkh.reshape(b, hk, rep, skp, d).sum(axis=2)
        dvh = dvh.reshape(b, hk, rep, skp, d).sum(axis=2)
    dk = dkh[:, :, :sk].astype(k.dtype)
    dv = dvh[:, :, :sk].astype(v.dtype)
    dq = dqh[:, :, :sq].astype(q.dtype)
    return dq, dk, dv, None, None


def flash_attention_bwd_jnp(q, k, v, do, o, lse, scale=None, causal=False,
                            segment_ids=None, blocks=None):
    """UNJITTED jnp twin of the fused Pallas backward (the
    ``quant_matmul_jnp`` parity contract).

    Takes paddle-layout [batch, seq, heads, head_dim] ``q/k/v/do`` plus
    the forward's ``o`` and logsumexp ``lse`` ([B, H, Sq], the second
    output of ``_fwd``), and replays the fused kernel's EXACT tile walk
    — the same padding, the same per-tile dot shapes and dimension
    numbers, the same accumulate order (k-blocks outer, q-blocks inner),
    the same masks and casts — so interpret-mode kernel grads are
    BITWISE equal on CPU for every geometry. Deliberately unjitted:
    jitted chains FMA-contract and drift the last ulp.

    Returns ``(dq, dk, dv)`` in paddle layout.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    seg_q = seg_k = None
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            seg_q, seg_k = segment_ids
        else:
            seg_q = seg_k = segment_ids
        seg_q = jnp.asarray(seg_q, jnp.int32)
        seg_k = jnp.asarray(seg_k, jnp.int32)
    q = jnp.swapaxes(q, 1, 2)   # -> [B, H, S, D]
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    do = jnp.swapaxes(do, 1, 2)
    o = jnp.swapaxes(o, 1, 2)
    b, hq, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    rep = hq // hk
    has_seg = seg_q is not None
    bq, bk = blocks if blocks is not None else _bwd_block_sizes(sq, sk)
    bq, bk = min(bq, sq), min(bk, sk)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp = _pad_to(q, 2, bq)
    dop = _pad_to(do, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    sqp, skp = qp.shape[2], kp.shape[2]
    nq, nk = sqp // bq, skp // bk
    lsep = jnp.broadcast_to(_pad_to(lse, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))
    dltp = jnp.broadcast_to(_pad_to(delta, 2, bq)[..., None],
                            (b, hq, sqp, _LANE))
    if has_seg:
        qsp = _pad_to(seg_q, 1, bq)
        ksp = _pad_to(seg_k, 1, bk)
    offset = sk - sq

    dqh = jnp.zeros((b, hq, sqp, d), jnp.float32)
    dkh = jnp.zeros((b, hq, skp, d), jnp.float32)
    dvh = jnp.zeros((b, hq, skp, d), jnp.float32)
    for ib in range(b):
        for ih in range(hq):
            dq_acc = jnp.zeros((sqp, d), jnp.float32)
            for ik in range(nk):
                kb = kp[ib, ih // rep,
                        ik * bk:(ik + 1) * bk].astype(jnp.float32)
                vb = vp[ib, ih // rep,
                        ik * bk:(ik + 1) * bk].astype(jnp.float32)
                dk_acc = jnp.zeros((bk, d), jnp.float32)
                dv_acc = jnp.zeros((bk, d), jnp.float32)
                lo = max(0, (ik * bk - offset) // bq) if causal else 0
                for iq in range(nq):
                    if iq < lo:
                        continue
                    qb = qp[ib, ih, iq * bq:(iq + 1) * bq] \
                        .astype(jnp.float32) * scale
                    dob = dop[ib, ih, iq * bq:(iq + 1) * bq] \
                        .astype(jnp.float32)
                    lse_t = lsep[ib, ih, iq * bq:(iq + 1) * bq, 0:1]
                    dlt_t = dltp[ib, ih, iq * bq:(iq + 1) * bq, 0:1]
                    s = jax.lax.dot_general(
                        qb, kb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    rows = (jax.lax.broadcasted_iota(
                        jnp.int32, (bq, bk), 0) + iq * bq)
                    cols = (jax.lax.broadcasted_iota(
                        jnp.int32, (bq, bk), 1) + ik * bk)
                    mask = (cols < sk) & (rows < sq)
                    if causal:
                        mask = mask & (rows + offset >= cols)
                    if has_seg:
                        qs = qsp[ib, iq * bq:(iq + 1) * bq]
                        ks = ksp[ib, ik * bk:(ik + 1) * bk]
                        mask = mask & (qs[:, None] == ks[None, :])
                    p = jnp.where(mask, jnp.exp(s - lse_t), 0.0)
                    dv_acc = dv_acc + jax.lax.dot_general(
                        p, dob, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    dp = jax.lax.dot_general(
                        dob, vb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    ds = p * (dp - dlt_t)
                    dk_acc = dk_acc + jax.lax.dot_general(
                        ds, qb, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    dq_acc = dq_acc.at[iq * bq:(iq + 1) * bq].set(
                        dq_acc[iq * bq:(iq + 1) * bq]
                        + jax.lax.dot_general(
                            ds, kb, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
                dkh = dkh.at[ib, ih, ik * bk:(ik + 1) * bk].set(dk_acc)
                dvh = dvh.at[ib, ih, ik * bk:(ik + 1) * bk].set(dv_acc)
            dqh = dqh.at[ib, ih].set(dq_acc * scale)
    if rep > 1:
        dkh = dkh.reshape(b, hk, rep, skp, d).sum(axis=2)
        dvh = dvh.reshape(b, hk, rep, skp, d).sum(axis=2)
    dk = dkh[:, :, :sk].astype(k.dtype)
    dv = dvh[:, :, :sk].astype(v.dtype)
    dq = dqh[:, :, :sq].astype(q.dtype)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, seg_q, seg_k, scale, causal, interpret,
                blocks=None, bwd_blocks=None):
    o, _ = _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks)
    return o


def _flash_fwd_rule(q, k, v, seg_q, seg_k, scale, causal, interpret,
                    blocks=None, bwd_blocks=None):
    o, lse = _fwd(q, k, v, seg_q, seg_k, scale, causal, interpret, blocks)
    return o, (q, k, v, seg_q, seg_k, o, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


_TUNE_CANDIDATES = ((128, 128), (256, 256), (256, 512), (512, 256),
                    (512, 512), (512, 1024), (1024, 512), (1024, 1024))
# backward candidates: the fused backward kernel carries full-row
# q/do/dq VMEM buffers plus per-k-block dk/dv accumulators — a larger
# fixed footprint than the forward (the old shared-candidate scheme let
# the backward inherit forward-biased winners; see the validate() note
# below) — so the sweep stays at or below 512x512 tiles where the
# accumulators plus the k/v tiles cannot tip a full-row budget over.
_TUNE_BWD_CANDIDATES = ((128, 128), (128, 256), (256, 128), (256, 256),
                        (256, 512), (512, 256), (512, 512))


def _scan_slope(make_runner, args, r1=4, r2=24):
    """Dispatch-free kernel timing: ``reps`` applications scanned inside
    ONE jit (the q input is index-perturbed so XLA cannot CSE the
    iterations; the scan compiles each kernel once regardless of reps).
    The difference between two rep counts is pure kernel time — constant
    dispatch/tunnel latency cancels; per-call wall timing over a
    network-attached chip is jitter-dominated and picks wrong winners.
    Returns seconds/rep, or inf when below timing resolution (noise must
    never crown a winner)."""
    def _timed(reps):
        f = make_runner(reps)
        out = f(*args)
        float(jax.device_get(out.ravel()[0]))  # compile/warm + sync
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(*args)
            float(jax.device_get(out.ravel()[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    slope = (_timed(r2) - _timed(r1)) / (r2 - r1)
    return slope if slope > 0 else float("inf")


def _tuned_entry(entry, candidates, qt, kt, causal, make_runner,
                 validate):
    """Shared cache-probe / sweep / fallback protocol for both flash
    autotune entries. Under a trace (tracer inputs) only cache HITS
    apply — the shapes are static so the key is known; the measuring
    sweep runs when inputs are concrete (first eager call, or an
    explicit warmup like bench.py's). On a sweep where every candidate
    failed or timed below resolution, fall back to the measured
    defaults rather than crashing the call (nothing is cached, so a
    later quieter run can still tune)."""
    from . import autotune as at
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    cands = [c for c in candidates if c[0] <= sq and c[1] <= sk]
    if len(cands) <= 1:
        return None
    sig = f"b{b}h{h}sq{sq}sk{sk}d{d}c{int(causal)}"
    cached = at._load_cache().get(f"{at._device_kind()}|{entry}|{sig}")
    if cached is not None:
        for c in cands:
            if at._same_candidate(c, cached):
                return tuple(c)
    if isinstance(qt, jax.core.Tracer):
        return None  # no timing possible mid-trace; use defaults
    runners = {}

    def memo_runner(cand, reps):
        f = runners.get((cand, reps))
        if f is None:
            f = runners[(cand, reps)] = jax.jit(make_runner(cand, reps))
        return f

    def measure(cand):
        return _scan_slope(lambda reps: memo_runner(cand, reps),
                           (qt, kt, kt))

    try:
        return tuple(at.autotune(entry, sig, cands, None,
                                 measure=measure, validate=validate))
    except RuntimeError:
        return None


def _autotuned_blocks(qt, kt, scale, causal):
    """FORWARD block-size selection through the autotune cache (SURVEY
    C14; see autotune.py). The backward tunes separately
    (``_autotuned_bwd_blocks``) — its fused kernel has different VMEM
    pressure and different winners, and fwd+bwd-blended timing used to
    bias both."""

    def make_runner(cand, reps):
        def chained(a, bb, cc, _n=reps, _cand=tuple(cand)):
            def body(c, i):
                o = _flash_bhsd(a + i.astype(a.dtype) * 1e-6, bb, cc,
                                None, None, scale, causal, False,
                                _cand)
                return c + o.astype(a.dtype), None
            z = jnp.zeros(a.shape, a.dtype)
            return jax.lax.scan(body, z, jnp.arange(_n))[0]
        return chained

    def validate(cand):
        # the measuring jit may fuse/lay out differently than the real
        # call: compile+run the forward in the caller's real eager
        # context — a scoped-vmem overflow disqualifies the candidate
        # and the next-best wins.
        o = _flash_bhsd(qt, kt, kt, None, None, scale, causal, False,
                        tuple(cand))
        float(jax.device_get(o.ravel()[0]))  # force execution

    return _tuned_entry("flash_attention", _TUNE_CANDIDATES, qt, kt,
                        causal, make_runner, validate)


def _autotuned_bwd_blocks(qt, kt, scale, causal, fwd_blocks):
    """BACKWARD block-size selection: its own ``flash_attention_bwd``
    autotune entry over backward-specific candidates
    (``_TUNE_BWD_CANDIDATES`` — the fused kernel's VMEM footprint is
    larger than the forward's, so forward-biased 1024-tile candidates
    are excluded up front). The timed program is the full fwd+bwd chain
    with the FORWARD blocks pinned to the already-tuned winner: the
    forward term is constant across candidates, so the slope ranks the
    backward kernels alone."""

    def make_runner(cand, reps):
        grad = jax.grad(
            lambda a, bb, cc, _cand=tuple(cand): _flash_bhsd(
                a, bb, cc, None, None, scale, causal, False,
                fwd_blocks, _cand).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        def chained(a, bb, cc, _n=reps):
            def body(c, i):
                # every grad output must feed the carry: an unused
                # dk/dv would let XLA dead-code-eliminate their
                # accumulation from the timed program. dk/dv fold in
                # as scalars so rectangular attention (sq != sk)
                # stays timeable.
                dq, dk, dv = grad(a + i.astype(a.dtype) * 1e-6, bb, cc)
                extra = (dk.sum() + dv.sum()).astype(a.dtype)
                return c + dq.astype(a.dtype) + extra, None
            z = jnp.zeros(a.shape, a.dtype)
            return jax.lax.scan(body, z, jnp.arange(_n))[0]
        return chained

    def validate(cand):
        # the fused backward has the larger vmem footprint (full-row
        # q/do/dq buffers + the dk/dv accumulators). Compile+run fwd AND
        # bwd in the caller's real eager context — a scoped-vmem
        # overflow disqualifies the candidate and the next-best wins.
        def f(a, bb, cc):
            return _flash_bhsd(
                a, bb, cc, None, None, scale, causal, False, fwd_blocks,
                tuple(cand)).astype(jnp.float32).sum()
        grads = jax.grad(f, argnums=(0, 1, 2))(qt, kt, kt)
        float(jax.device_get(grads[0].ravel()[0]))  # force execution

    return _tuned_entry("flash_attention_bwd", _TUNE_BWD_CANDIDATES,
                        qt, kt, causal, make_runner, validate)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None,
                    blocks=None, segment_ids=None, bwd_blocks=None):
    """Flash attention in paddle layout [batch, seq, num_heads, head_dim].

    ``num_heads(q)`` may be a multiple of ``num_heads(k) == num_heads(v)``
    (grouped-query attention). Returns [batch, seq_q, num_heads, head_dim].
    ``blocks``: optional (block_q, block_k) override; with autotuning
    enabled (``incubate.autotune.set_config``) the best pair is measured
    on-device and cached per shape. ``bwd_blocks``: the same for the
    fused backward kernel (its own ``flash_attention_bwd`` autotune
    entry — backward winners differ from forward ones).
    ``segment_ids``: varlen/packed-sequence support (the capability of the
    reference's ``flash_attn_varlen_fwd``,
    ``paddle/phi/kernels/gpu/flash_attn_kernel.cu:91``): an int array
    [batch, seq] (shared q/kv when lengths match) or a pair
    ``(q_seg [B,Sq], kv_seg [B,Sk])``; attention is confined to positions
    with equal segment id, composing with ``causal``.
    """
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hk = q.shape[2], k.shape[2]
    if hk == 0 or hq % hk != 0:
        raise ValueError(
            f"flash_attention: query heads ({hq}) must be a multiple of "
            f"key/value heads ({hk}) for grouped-query attention")
    seg_q = seg_k = None
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            seg_q, seg_k = segment_ids
        else:
            if q.shape[1] != k.shape[1]:
                raise ValueError(
                    "flash_attention: a single segment_ids array needs "
                    "seq_q == seq_k; pass (q_seg, kv_seg) otherwise")
            seg_q = seg_k = segment_ids
        seg_q = jnp.asarray(seg_q, jnp.int32)
        seg_k = jnp.asarray(seg_k, jnp.int32)
    qt = jnp.swapaxes(q, 1, 2)  # -> [B, H, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if not interpret and segment_ids is None:
        from . import autotune as at
        if at.enabled():
            # a caller-pinned blocks= opts OUT of tuning entirely (the
            # pre-split behavior; the pinned pair also drives the
            # backward through _bwd's fallback chain)
            if blocks is None:
                blocks = _autotuned_blocks(qt, kt, float(scale),
                                           bool(causal))
                if bwd_blocks is None:
                    bwd_blocks = _autotuned_bwd_blocks(
                        qt, kt, float(scale), bool(causal), blocks)
    o = _flash_bhsd(qt, kt, vt, seg_q, seg_k, float(scale), bool(causal),
                    bool(interpret), blocks, bwd_blocks)
    return jnp.swapaxes(o, 1, 2)

"""Pallas TPU ragged paged-KV attention (decode + mixed prefill/decode).

Capability analog of the reference's paged/block KV serving kernels
(``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``,
``masked_multihead_attention_kernel.cu``) in the TPU-native shape of
"Ragged Paged Attention" (arxiv 2604.15464 / PAPERS.md):

* the KV cache lives in a PAGE POOL ``[num_kv_heads, total_pages,
  page_size, head_dim]``; each sequence owns a list of page indices (its
  block table) instead of a contiguous ``max_len`` slab, so HBM scales
  with tokens actually generated;
* the grid is COMPACTED through the scalar-prefetch channel: the host
  (or the enclosing jit) computes cumulative per-sequence kv-block
  counts and flattens the real (sequence, q-block, kv-block) work items
  onto one grid axis — programs exist only for blocks inside each
  sequence's true length (plus a static-budget tail that exits
  immediately).  The previous kernel's ``pl.when`` skipped *compute*
  past the length but its BlockSpec still DMA'd every page slot of
  every sequence; here the page fetches are issued inside the kernel
  (``pltpu.make_async_copy`` from the HBM-resident pool), so a skipped
  block moves no bytes at all;
* each program walks ``pages_per_block`` pages, amortizing the
  sublane-padded q block across ``pages_per_block * page_size`` KV
  tokens per grid step (the one-page-per-program version re-fetched the
  q block once per page). ``pages_per_block`` is an autotunable free
  parameter (``ops/pallas/autotune.py``);
* RAGGED batches: ``ragged_paged_attention`` takes packed q tokens with
  per-sequence ``q_lens`` — decode rows (q_len 1) and prefill rows
  (q_len = prompt chunk) share ONE kernel call, the shape a
  continuous-batching step needs (``paddle_tpu/inference/engine.py``).
  Causality is positional: q token ``i`` of a sequence attends kv
  positions ``<= kv_len - q_len + i``;
* online softmax across a sequence's kv blocks in VMEM scratch (same
  flash recurrence as flash_attention.py); GQA by grouping the
  ``rep = Hq // Hk`` query heads of a kv head into the sublane
  dimension.

* INT8 KV pages (ISSUE 7): when the pools are int8, per-page scale
  side-pools [Hk, P, page_size] (``quantization.kv_quantize``) are
  DMA'd alongside each data page and the dequant happens in VMEM right
  after the copy completes — attention reads a QUARTER of the fp32 KV
  bytes per step, which is the serving roofline term
  (benchmarks/serving_bench.py), and no float page ever exists in HBM.

Public entries: ``paged_decode_attention`` (one token per sequence —
the ``models.generate(kv_cache='paged')`` path, API-compatible with the
previous kernel) and ``ragged_paged_attention`` (mixed token counts —
the serving engine path).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANE = 128    # lane width for per-row stats kept in VMEM scratch
_MIN_SUB = 8   # Mosaic sublane minimum for the q-block row dimension


def _cdiv(a, b):
    return (a + b - 1) // b


def _row_pad(q_block, rep):
    """Smallest ``rep_p >= rep`` with ``q_block * rep_p`` a sublane
    multiple — the kernel's q-block row count is ``q_block * rep_p``
    ((token, q-head-of-group) pairs stacked in the sublane dim)."""
    rep_p = rep
    while (q_block * rep_p) % _MIN_SUB:
        rep_p += 1
    return rep_p


# --------------------------------------------------------------------------
# work-item planning (grid compaction)
# --------------------------------------------------------------------------

def _plan_items(kv_lens, q_lens, *, q_block, blk_tokens, nqb_total,
                item_budget):
    """Flatten the ragged (sequence, q-block, kv-block) work triples onto
    one grid axis.  Pure jnp — runs on concrete arrays (eager call) and
    on tracers (inside a jitted serving step; the arrays ride the
    scalar-prefetch channel, so changing lengths never recompile).

    Returns int32 arrays sized by the STATIC budgets:
      seq[i], qb[i]   — owning sequence / q block within it
      kb[i]           — kv block within the sequence
      qbg[i]          — global q-block index (output/q BlockSpec target;
                        budget tail repeats the last live value so the
                        pipeline never flaps blocks)
      first[i]/last[i]— 1 on the first/last kv block of a q block
                        (accumulator init / output flush), 0 on the tail
      nitems          — [1] live item count
    """
    kv_lens = kv_lens.astype(jnp.int32)
    q_lens = q_lens.astype(jnp.int32)
    nseq = q_lens.shape[0]
    nqb = _cdiv(q_lens, q_block)                     # [B] q blocks/seq
    cq = jnp.cumsum(nqb)
    total_qb = cq[-1]
    seg_blk = cq - nqb                               # seq -> first q block

    j = jnp.arange(nqb_total, dtype=jnp.int32)       # flat q-block axis
    seq_j = jnp.minimum(jnp.searchsorted(cq, j, side="right"),
                        nseq - 1).astype(jnp.int32)
    qb_j = j - seg_blk[seq_j]
    # causal truncation: q block qb only needs kv up to its last token's
    # position + 1 = kv_len - q_len + (qb+1)*q_block, clamped to kv_len
    kv_need = jnp.minimum(kv_lens[seq_j],
                          kv_lens[seq_j] - q_lens[seq_j]
                          + (qb_j + 1) * q_block)
    nk_j = jnp.where(j < total_qb, _cdiv(kv_need, blk_tokens), 0)
    ck = jnp.cumsum(nk_j)
    nitems = ck[-1]

    i = jnp.arange(item_budget, dtype=jnp.int32)     # flat item axis
    j_i = jnp.minimum(jnp.searchsorted(ck, i, side="right"),
                      nqb_total - 1).astype(jnp.int32)
    kb_i = i - (ck[j_i] - nk_j[j_i])
    seq_i = seq_j[j_i]
    qbg_i = seg_blk[seq_i] + qb_j[j_i]
    live = i < nitems
    last_qbg = qbg_i[jnp.maximum(nitems - 1, 0)]
    qbg_i = jnp.where(live, qbg_i, last_qbg)
    first_i = (live & (kb_i == 0)).astype(jnp.int32)
    last_i = (live & (kb_i == nk_j[j_i] - 1)).astype(jnp.int32)
    return (seq_i, qb_j[j_i].astype(jnp.int32), kb_i.astype(jnp.int32),
            qbg_i.astype(jnp.int32), first_i, last_i,
            jnp.reshape(nitems, (1,)).astype(jnp.int32))


def _count_items(kv_lens, q_lens, q_block, blk_tokens):
    """Exact live-item count for CONCRETE lengths (numpy) — eager calls
    size the grid tightly instead of paying the worst-case budget."""
    kv = np.asarray(kv_lens, np.int64)
    ql = np.asarray(q_lens, np.int64)
    total = 0
    for b in range(kv.shape[0]):
        for qb in range(int(_cdiv(ql[b], q_block))):
            need = min(kv[b], kv[b] - ql[b] + (qb + 1) * q_block)
            total += int(_cdiv(need, blk_tokens))
    return total


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------

def _ragged_kernel(seq_ref, qb_ref, kb_ref, qbg_ref, first_ref, last_ref,
                   nitems_ref, bt_ref, kvl_ref, ql_ref,
                   q_ref, *refs,
                   scale, page_size, pages_per_block, q_block, rep_p,
                   quant):
    """One compacted work item: walk ``pages_per_block`` pages of one
    sequence's kv block against one q block.  Scalars (prefetched):
    item maps + block tables [B, NP] + kv/q lengths [B].  q/o blocks:
    [1, 1, q_block*rep_p, D].  k/v pools stay in HBM; pages are DMA'd
    into VMEM scratch only for live items.

    ``quant``: the pools are int8 and two per-page scale side-pools
    [Hk, P, page_size] ride along — each page's scale vector is DMA'd
    with its data page and the dequant (one VPU multiply per token row)
    happens right here in VMEM, so quantized attention reads a QUARTER
    of the fp32 KV bytes per step and never materializes a float page
    in HBM (PAPERS.md #3's fuse-dequant-into-the-consumer argument
    applied to the DMA loop)."""
    if quant:
        (k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, m_s, l_s, acc_s,
         kbuf, vbuf, ksbuf, vsbuf, ksem, vsem, kssem, vssem) = refs
    else:
        (k_hbm, v_hbm, o_ref, m_s, l_s, acc_s,
         kbuf, vbuf, ksem, vsem) = refs
    i = pl.program_id(1)
    ih = pl.program_id(0)
    live = i < nitems_ref[0]
    blk_tokens = pages_per_block * page_size

    @pl.when(first_ref[i] == 1)
    def _init():
        m_s[...] = jnp.full(m_s.shape, NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    @pl.when(live)
    def _fetch_and_accumulate():
        b = seq_ref[i]
        kb = kb_ref[i]
        kv_len = kvl_ref[b]
        npg = _cdiv(kv_len, page_size)          # pages this seq occupies
        page0 = kb * pages_per_block

        def _copies(p, pid):
            cps = [pltpu.make_async_copy(k_hbm.at[ih, pid], kbuf.at[p],
                                         ksem.at[p]),
                   pltpu.make_async_copy(v_hbm.at[ih, pid], vbuf.at[p],
                                         vsem.at[p])]
            if quant:
                cps.append(pltpu.make_async_copy(
                    ks_hbm.at[ih, pid], ksbuf.at[p], kssem.at[p]))
                cps.append(pltpu.make_async_copy(
                    vs_hbm.at[ih, pid], vsbuf.at[p], vssem.at[p]))
            return cps

        for p in range(pages_per_block):        # static unroll
            @pl.when(page0 + p < npg)
            def _start(p=p):
                pid = bt_ref[b, page0 + p]
                for c in _copies(p, pid):
                    c.start()
        for p in range(pages_per_block):
            @pl.when(page0 + p < npg)
            def _wait(p=p):
                pid = bt_ref[b, page0 + p]
                for c in _copies(p, pid):
                    c.wait()

        q = q_ref[0, 0].astype(jnp.float32) * scale      # [rows, D]
        kblk = kbuf[...].reshape(blk_tokens, -1).astype(jnp.float32)
        vblk = vbuf[...].reshape(blk_tokens, -1).astype(jnp.float32)
        if quant:   # in-DMA-loop dequant: int8 row * its per-slot scale
            kblk = kblk * ksbuf[...].reshape(blk_tokens, 1)
            vblk = vblk * vsbuf[...].reshape(blk_tokens, 1)
        # tokens past kv_len sit in pages never fetched this item —
        # uninitialized VMEM. Zero them BEFORE the dots: the softmax
        # mask alone is not enough (0-weight x NaN garbage = NaN in the
        # p@v accumulation).
        tok_valid = (kb * blk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (blk_tokens, 1), 0)) < kv_len
        kblk = jnp.where(tok_valid, kblk, 0.0)
        vblk = jnp.where(tok_valid, vblk, 0.0)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        # causal/ragged mask: q row r is token qb*q_block + r // rep_p
        # of its sequence, sitting at absolute position kv_len - q_len
        # + that index; kv column c is absolute position kb*blk + c.
        # (stale scratch rows from pages past npg mask out here too.)
        kv_pos = kb * blk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep_p
        q_pos = kv_len - ql_ref[b] + qb_ref[i] * q_block + qi
        s = jnp.where(kv_pos <= q_pos, s, NEG_INF)

        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p_ = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p_, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p_, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(last_ref[i] == 1)
    def _finish():
        l = l_s[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)


def _ragged_call(qx, k_pages, v_pages, bt, kv_lens, q_lens, plan,
                 item_budget, *, scale, q_block, rep_p, pages_per_block,
                 interpret, k_scales=None, v_scales=None):
    """Shared pallas_call: ``qx`` is the blocked q layout
    [Hk, n_q_blocks, q_block*rep_p, D]; returns the same layout.
    ``k_scales``/``v_scales`` [Hk, P, page_size] switch on the int8
    in-kernel-dequant variant."""
    hk, nqb_total, rows, d = qx.shape
    page_size = k_pages.shape[2]
    grid = (hk, item_budget)
    quant = k_scales is not None
    kernel = functools.partial(
        _ragged_kernel, scale=float(scale), page_size=page_size,
        pages_per_block=pages_per_block, q_block=q_block, rep_p=rep_p,
        quant=quant)
    kv_dt = k_pages.dtype

    def q_index(ih, i, seq, qb, kb, qbg, first, last, nitems, btm, kvl,
                ql):
        return (ih, qbg[i], 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, rows, d), q_index),
        pl.BlockSpec(memory_space=pltpu.ANY),   # k page pool
        pl.BlockSpec(memory_space=pltpu.ANY),   # v page pool
    ]
    scratch = [
        pltpu.VMEM((rows, _LANE), jnp.float32),
        pltpu.VMEM((rows, _LANE), jnp.float32),
        pltpu.VMEM((rows, d), jnp.float32),
        pltpu.VMEM((pages_per_block, page_size, d), kv_dt),
        pltpu.VMEM((pages_per_block, page_size, d), kv_dt),
    ]
    extra = []
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),  # k scales
                     pl.BlockSpec(memory_space=pltpu.ANY)]  # v scales
        scratch += [pltpu.VMEM((pages_per_block, page_size), jnp.float32),
                    pltpu.VMEM((pages_per_block, page_size), jnp.float32)]
        extra = [k_scales.astype(jnp.float32),
                 v_scales.astype(jnp.float32)]
    scratch += [pltpu.SemaphoreType.DMA((pages_per_block,)),
                pltpu.SemaphoreType.DMA((pages_per_block,))]
    if quant:
        scratch += [pltpu.SemaphoreType.DMA((pages_per_block,)),
                    pltpu.SemaphoreType.DMA((pages_per_block,))]

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=10,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, rows, d), q_index),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct(qx.shape, qx.dtype),
        interpret=interpret,
    )(*plan, bt.astype(jnp.int32), kv_lens.astype(jnp.int32),
      q_lens.astype(jnp.int32), qx, k_pages, v_pages, *extra)


# --------------------------------------------------------------------------
# public entries
# --------------------------------------------------------------------------

def _resolve(interpret, scale, d):
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    return interpret, scale


def _is_concrete(*xs):
    return not any(isinstance(x, jax.core.Tracer) for x in xs)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_lens, q_block=8, pages_per_block=None,
                           scale=None, interpret=None, item_budget=None,
                           k_scales=None, v_scales=None):
    """Attention for a continuously-batched step over a paged KV cache.

    q: [T, Hq, D] — tokens of ALL sequences packed in sequence order,
      each sequence's segment padded up to a multiple of ``q_block``
      (segment b starts at ``q_block * sum(ceil(q_lens[:b]/q_block))``);
    k_pages/v_pages: [Hk, total_pages, page_size, D] page pools — the
      new tokens' K/V must already be written to their (page, slot);
    block_tables: [B, pages_per_seq] int32 page ids per sequence;
    kv_lens: [B] total kv tokens per sequence INCLUDING this step's;
    q_lens: [B] tokens each sequence contributes this step (0 = sits
      out; decode rows 1; prefill rows the prompt-chunk length).
    k_scales/v_scales: [Hk, total_pages, page_size] f32 side-pools for
      INT8 pools (``quantization.kv_quantize`` layout): pages dequantize
      inside the kernel's DMA loop, so a quantized step moves a quarter
      of the fp32 KV bytes.  Both or neither.

    Returns [T, Hq, D] (rows of segment padding are garbage — callers
    gather real token rows only).  Mixed prefill+decode batches are the
    point: one call, one grid, per-sequence causal offsets.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("ragged_paged_attention: pass both k_scales "
                         "and v_scales or neither")
    t, hq, d = q.shape
    hk, _, page_size, _ = k_pages.shape
    if hk == 0 or hq % hk != 0:
        raise ValueError(f"ragged_paged_attention: {hq} q heads not a "
                         f"multiple of {hk} kv heads")
    rep = hq // hk
    rep_p = _row_pad(q_block, rep)
    npages = block_tables.shape[1]
    interpret, scale = _resolve(interpret, scale, d)
    if pages_per_block is None:
        pages_per_block = pick_pages_per_block(
            hk, page_size, d, npages, q_heads=hq)
    pages_per_block = max(1, min(int(pages_per_block), npages))
    blk_tokens = pages_per_block * page_size

    tp = _cdiv(t, q_block) * q_block
    if tp != t:
        q = jnp.pad(q, ((0, tp - t), (0, 0), (0, 0)))
    nqb_total = tp // q_block
    if item_budget is None:
        if _is_concrete(kv_lens, q_lens):
            item_budget = max(
                1, _count_items(kv_lens, q_lens, q_block, blk_tokens))
        else:
            item_budget = nqb_total * _cdiv(npages, pages_per_block)
    plan = _plan_items(jnp.asarray(kv_lens), jnp.asarray(q_lens),
                       q_block=q_block, blk_tokens=blk_tokens,
                       nqb_total=nqb_total, item_budget=item_budget)

    qg = q.reshape(tp, hk, rep, d)
    if rep_p != rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rep_p - rep), (0, 0)))
    qx = jnp.transpose(qg, (1, 0, 2, 3)).reshape(
        hk, nqb_total, q_block * rep_p, d)

    out = _ragged_call(qx, k_pages, v_pages,
                       jnp.asarray(block_tables), jnp.asarray(kv_lens),
                       jnp.asarray(q_lens), plan, item_budget,
                       scale=scale, q_block=q_block, rep_p=rep_p,
                       pages_per_block=pages_per_block,
                       interpret=interpret, k_scales=k_scales,
                       v_scales=v_scales)
    out = out.reshape(hk, tp, rep_p, d)[:, :t, :rep]
    return jnp.transpose(out, (1, 0, 2, 3)).reshape(t, hq, d)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           scale=None, interpret=None,
                           pages_per_block=None, k_scales=None,
                           v_scales=None):
    """One decode step of attention over a paged KV cache.

    q: [B, Hq, D] (one query token per sequence);
    k_pages/v_pages: [Hk, total_pages, page_size, D] page pool;
    block_tables: [B, pages_per_seq] int32 — global page ids per
      sequence (may be traced: the serving engine re-points tables at
      admission without recompiling);
    seq_lens: [B] int32 — valid tokens (including the current one);
    k_scales/v_scales: int8-pool scale side-pools (see
      ``ragged_paged_attention``).
    Returns [B, Hq, D]. ``Hq`` must be a multiple of ``Hk`` (GQA).

    This is ``ragged_paged_attention`` with every sequence contributing
    one token (q_block=1): the B q blocks flatten onto the compacted
    grid, each program covering ``pages_per_block`` pages.
    """
    b = q.shape[0]
    return ragged_paged_attention(
        q, k_pages, v_pages, block_tables,
        jnp.asarray(seq_lens), jnp.ones((b,), jnp.int32),
        q_block=1, pages_per_block=pages_per_block, scale=scale,
        interpret=interpret, k_scales=k_scales, v_scales=v_scales)


# --------------------------------------------------------------------------
# pages_per_block selection (heuristic default + autotune)
# --------------------------------------------------------------------------

# ~512 kv tokens per grid step amortizes the q-block fetch and the
# per-program control overhead while 2 * ppb * page_size * D * 4B of
# scratch stays far under VMEM; capped by the table width.
_TARGET_BLK_TOKENS = 512
_VMEM_CAP_BYTES = 4 * 1024 * 1024


def default_pages_per_block(page_size, npages, head_dim):
    per_page = 2 * page_size * head_dim * 4
    cap = max(1, _VMEM_CAP_BYTES // max(per_page, 1))
    tgt = max(1, _TARGET_BLK_TOKENS // max(page_size, 1))
    p = 1
    while p * 2 <= min(tgt, npages, cap):
        p *= 2
    return p


def _tune_candidates(page_size, npages, head_dim):
    per_page = 2 * page_size * head_dim * 4
    cap = max(1, _VMEM_CAP_BYTES // max(per_page, 1))
    cands, p = [], 1
    while p <= min(npages, cap):
        cands.append(p)
        p *= 2
    return cands


def pick_pages_per_block(hk, page_size, head_dim, npages, q_heads=None):
    """``pages_per_block`` through the autotune cache (SURVEY C14).
    Cache hits apply everywhere (including under a trace — the key is
    static); the measuring sweep runs only when autotuning is enabled,
    on synthetic decode shapes, so a first serving call never stalls."""
    from . import autotune as at
    cands = _tune_candidates(page_size, npages, head_dim)
    fallback = default_pages_per_block(page_size, npages, head_dim)
    if len(cands) <= 1:
        return fallback
    sig = f"hk{hk}_ps{page_size}_d{head_dim}_np{npages}"
    try:
        cached = at._load_cache().get(
            f"{at._device_kind()}|paged_attention_ppb|{sig}")
    except Exception:
        cached = None
    if cached is not None and cached in cands:
        return int(cached)
    if not at.enabled():
        return fallback

    hq = q_heads or hk
    b = 4
    rng = np.random.default_rng(0)
    qs = jnp.asarray(rng.normal(size=(b, hq, head_dim)), jnp.float32)
    pool = jnp.asarray(rng.normal(
        size=(hk, b * npages, page_size, head_dim)), jnp.float32)
    bt = jnp.arange(b * npages, dtype=jnp.int32).reshape(b, npages)
    lens = jnp.full((b,), npages * page_size, jnp.int32)

    def run(cand):
        out = paged_decode_attention(qs, pool, pool, bt, lens,
                                     pages_per_block=int(cand))
        jax.block_until_ready(out)

    try:
        return int(at.autotune("paged_attention_ppb", sig, cands, run))
    except Exception:
        return fallback

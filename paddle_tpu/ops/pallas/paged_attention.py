"""Pallas TPU paged-KV decode attention.

Capability analog of the reference's paged/block KV serving kernels
(``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``,
``masked_multihead_attention_kernel.cu``) — TPU-native design:

* the KV cache lives in a PAGE POOL ``[num_kv_heads, total_pages,
  page_size, head_dim]``; each sequence owns a list of page indices (its
  block table) instead of a contiguous ``max_len`` slab, so HBM scales with
  tokens actually generated and attention cost scales with the *current*
  length (the dense cache path computes over ``max_len`` every step);
* one decode step = grid ``(batch, kv_head, page)``; the block table and
  sequence lengths ride the scalar-prefetch channel so the BlockSpec index
  map gathers exactly the pages each sequence owns — no host gather, no
  materialized contiguous copy;
* online softmax across pages in VMEM scratch (same flash recurrence as
  flash_attention.py), GQA by grouping the ``rep = Hq // Hk`` query heads
  of a kv head into the sublane dimension of one program.

Public entry: ``paged_decode_attention(q, k_pages, v_pages, block_tables,
seq_lens)``. Decode-only (one query token per sequence) — prefill uses the
regular flash kernel.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANE = 128    # lane width for per-row stats kept in VMEM scratch
_MIN_SUB = 8   # Mosaic sublane minimum: q-head group padded up to this


def _kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
            m_s, l_s, acc_s, *, scale, page_size, npages):
    """One (b, kv_head, page) program. Scalars: bt [B, NP] page table,
    sl [B] sequence lengths. Blocks: q/o [1, 1, rep_p, D]; k/v page
    [1, 1, page_size, D]. Scratch: m/l [rep_p, _LANE], acc [rep_p, D]."""
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_s[...] = jnp.full(m_s.shape, NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    @pl.when(i * page_size < sl_ref[b])  # skip pages past the seq length
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale    # [rep_p, D]
        kb = k_ref[0, 0].astype(jnp.float32)           # [ps, D]
        vb = v_ref[0, 0].astype(jnp.float32)

        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
               + i * page_size)
        s = jnp.where(pos < sl_ref[b], s, NEG_INF)

        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                         # [rep_p, ps]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(i == npages - 1)
    def _finish():
        l = l_s[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           scale=None, interpret=None):
    """One decode step of attention over a paged KV cache.

    q: [B, Hq, D] (one query token per sequence);
    k_pages/v_pages: [Hk, total_pages, page_size, D] page pool;
    block_tables: [B, pages_per_seq] int32 — global page ids per sequence;
    seq_lens: [B] int32 — valid tokens (including the current one).
    Returns [B, Hq, D]. ``Hq`` must be a multiple of ``Hk`` (GQA).
    """
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    b, hq, d = q.shape
    hk, _, page_size, _ = k_pages.shape
    if hk == 0 or hq % hk != 0:
        raise ValueError(f"paged_decode_attention: {hq} q heads not a "
                         f"multiple of {hk} kv heads")
    rep = hq // hk
    rep_p = max(rep, _MIN_SUB)
    npages = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    qg = q.reshape(b, hk, rep, d)
    if rep_p != rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rep_p - rep), (0, 0)))

    grid = (b, hk, npages)
    kernel = functools.partial(_kernel, scale=float(scale),
                               page_size=page_size, npages=npages)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rep_p, d),
                             lambda ib, ih, ip, bt, sl: (ib, ih, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ib, ih, ip, bt, sl:
                             (ih, bt[ib, ip], 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ib, ih, ip, bt, sl:
                             (ih, bt[ib, ip], 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rep_p, d),
                lambda ib, ih, ip, bt, sl: (ib, ih, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rep_p, _LANE), jnp.float32),
                pltpu.VMEM((rep_p, _LANE), jnp.float32),
                pltpu.VMEM((rep_p, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hk, rep_p, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out[:, :, :rep].reshape(b, hq, d)

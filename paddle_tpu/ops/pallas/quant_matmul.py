"""Pallas TPU fused weight-only int8 matmul: ``y = (x @ W_int8) * scale``.

Capability analog of the reference's weight-only GEMMs
(``paddle/phi/kernels/fusion/cutlass/`` and
``weight_only_linear_kernel.cu``), in the operator-fusion shape argued
by PAPERS.md #3 ("Operator Fusion for LLM Inference"): the int8->float
dequantization must FUSE into the consuming matmul instead of
materializing a float weight tensor in HBM.  Weight bytes are the
serving roofline at decode (benchmarks/serving_bench.py computes the
HBM floor from exactly those bytes) — reading W as int8 quarters the
dominant term.

Key algebraic point: per-OUT-CHANNEL scales commute with the K
reduction (``sum_k x[m,k] * (q[k,n] * s[n]) == s[n] * sum_k x[m,k] *
q[k,n]``), so the kernel runs the MXU dot on the raw int8 block cast to
f32 and applies the scale ONCE per output tile after the reduction —
dequant costs one VPU multiply per output element instead of one per
weight element.

Two interchangeable implementations with identical arithmetic (the
fused-optimizer precedent, ``ops/pallas/fused_optimizer.py``):

- ``jnp`` — one ``dot_general`` (f32 accumulate) times the scale row.
  Deliberately UNJITTED: it is the CPU-CI implementation and the
  bit-exactness reference the interpret-mode kernel is pinned against
  (``tests/test_quantization.py``).
- ``pallas`` — grid ``(M/bm, N/bn, K/bk)`` with an f32 VMEM accumulator;
  ``bk`` covers all of K whenever it fits VMEM (the common serving
  case), making each output tile ONE dot — bitwise against the twin.
  Block sizes are an autotune entry (``quant_matmul_blocks``).

``weight_only_matmul`` is the public entry; ``quantization.
weight_only_linear`` and the ``WeightOnlyLinear`` layer route through
it, which is how a weight-quantized model served by ``models.generate``
or the continuous-batching engine reaches the fused path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MIN_SUB = 8          # f32 sublane minimum for the M tile
_LANE = 128           # lane width for the N (and padded K) tile
# bk covers all of K up to this bound; past it the K grid accumulates
# (keeps x/w blocks comfortably inside VMEM for 13B-class K)
_MAX_BK = 2048
_VMEM_CAP_BYTES = 6 * 1024 * 1024


def _cdiv(a, b):
    return (a + b - 1) // b


def _round_up(x, m):
    return _cdiv(x, m) * m


# --------------------------------------------------------------------------
# jnp twin — the arithmetic contract
# --------------------------------------------------------------------------

def _dot32(a, b):
    return jax.lax.dot_general(
        a.astype(jnp.float32), b.astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def quant_matmul_jnp(x, qw, scale, blocks=None):
    """``(x @ qw.astype(f32)) * scale`` with f32 accumulation.

    x [M, K] float, qw [K, N] int8, scale [N] float; returns [M, N]
    f32.  Unjitted on purpose (the fused-optimizer twin contract).

    ``blocks=(bm, bn, bk)`` replays the KERNEL's exact tile walk — the
    same per-tile dot shapes and ``acc += dot`` order — so interpret-
    mode parity is bitwise on every geometry (XLA's gemm is not
    guaranteed bit-stable across different tilings of one problem; the
    parity suite pins the kernel against this mirrored walk).  The
    default (None) is the one-dot form the CPU serving path uses.
    """
    sc = scale.astype(jnp.float32)
    if blocks is None:
        return _dot32(x, qw) * sc[None, :]
    bm, bn, bk = blocks
    m, k = x.shape
    n = qw.shape[1]
    if m % bm or n % bn or k % bk:
        raise ValueError(
            f"quant_matmul_jnp: blocks {blocks} must evenly divide the "
            f"(pre-padded) problem ({m}, {k}, {n}) — remainder tiles "
            f"would be silently dropped")
    rows = []
    for i in range(m // bm):
        row = []
        for j in range(n // bn):
            acc = jnp.zeros((bm, bn), jnp.float32)
            for kk in range(k // bk):
                acc = acc + _dot32(
                    x[i * bm:(i + 1) * bm, kk * bk:(kk + 1) * bk],
                    qw[kk * bk:(kk + 1) * bk, j * bn:(j + 1) * bn])
            row.append(acc * sc[None, j * bn:(j + 1) * bn])
        rows.append(jnp.concatenate(row, axis=1))
    return jnp.concatenate(rows, axis=0)


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------

def _kernel(x_ref, w_ref, s_ref, o_ref, acc_s, *, nk):
    k = pl.program_id(2) if nk > 1 else 0

    @pl.when(k == 0)
    def _init():
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    acc_s[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _finish():
        o_ref[...] = acc_s[...] * s_ref[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _pallas_matmul(x, qw, scale, bm, bn, bk, interpret):
    """x [M, K] f32-castable, qw [K, N] int8, scale [N]; M/K/N already
    padded to (bm, bk|LANE, bn) multiples.  Returns [M, N] f32.

    Wrapped in a custom VJP (pallas_call has no AD rule): the backward
    runs the jnp arithmetic — ``dx = (g * s) @ qw^T``, ``ds = sum_m
    g * (x @ qw)`` — so ``jax.grad`` through ``weight_only_linear``
    keeps working on TPU exactly as it did on the unfused
    ``x @ (qw * s)`` formulation."""
    m, k = x.shape
    n = qw.shape[1]
    nk = k // bk
    grid = (m // bm, n // bn, nk)
    kernel = functools.partial(_kernel, nk=nk)
    s2 = scale.astype(jnp.float32).reshape(1, n)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, qw, s2)


def _pallas_matmul_fwd(x, qw, scale, bm, bn, bk, interpret):
    return _pallas_matmul(x, qw, scale, bm, bn, bk, interpret), \
        (x, qw, scale)


def _pallas_matmul_bwd(bm, bn, bk, interpret, res, g):
    import numpy as np
    x, qw, scale = res
    g32 = g.astype(jnp.float32)
    gs = g32 * scale.astype(jnp.float32)[None, :]
    gx = jax.lax.dot_general(
        gs, qw.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    acc = _dot32(x, qw)
    gscale = jnp.sum(g32 * acc, axis=0).astype(scale.dtype)
    gqw = np.zeros(qw.shape, jax.dtypes.float0)  # int8: no tangent
    return gx, gqw, gscale


_pallas_matmul.defvjp(_pallas_matmul_fwd, _pallas_matmul_bwd)


# --------------------------------------------------------------------------
# block selection (heuristic default + autotune entry)
# --------------------------------------------------------------------------

def _pick_bk(k):
    """K-block for padded ``k``: all of K when it fits (one dot per
    output tile — bitwise vs the twin), else the largest LANE multiple
    <= _MAX_BK that divides k."""
    bk = k if k <= _MAX_BK else _LANE * max(1, _MAX_BK // _LANE)
    while k % bk:
        bk -= _LANE                         # padded k is a LANE multiple
    return bk


def default_blocks(m, k, n):
    """(bm, bn, bk) for the PADDED problem: every block EVENLY divides
    its axis (the grid must tile the output exactly), one K pass when it
    fits (bitwise vs the twin and no revisits), f32 x/w/acc tiles under
    the VMEM cap."""
    bk = _pick_bk(k)
    bm = _MIN_SUB
    while bm * 2 <= min(m, 256) and m % (bm * 2) == 0:
        bm *= 2
    bn = _LANE
    # the guard prices the DOUBLED bn (w tile int8+f32 cast, x tile,
    # acc tile) — the returned blocks must respect the cap themselves
    while bn * 2 <= min(n, 512) and n % (bn * 2) == 0 and \
            (bm * bk + bk * (bn * 2) * 2 + bm * (bn * 2)) * 4 \
            <= _VMEM_CAP_BYTES:
        bn *= 2
    return bm, bn, bk


def _tune_candidates(m, k, n):
    cands = []
    bk = _pick_bk(k)      # the bk the kernel will actually run with
    for bm in (8, 32, 128, 256):
        if bm > m or m % bm:
            continue
        for bn in (128, 256, 512):
            if bn > n or n % bn:
                continue
            if (bm * bk + bk * bn * 2 + bm * bn) * 4 > _VMEM_CAP_BYTES:
                continue
            cands.append((bm, bn))
    return cands


def pick_blocks(m, k, n):
    """Block sizes through the autotune cache (entry
    ``quant_matmul_blocks``; same contract as
    ``paged_attention.pick_pages_per_block``: cache hits apply
    everywhere, the measuring sweep runs only when autotuning is
    enabled)."""
    from . import autotune as at
    bm0, bn0, bk = default_blocks(m, k, n)
    cands = _tune_candidates(m, k, n)
    if len(cands) <= 1:
        return bm0, bn0, bk
    sig = f"m{m}_k{k}_n{n}"
    try:
        cached = at._load_cache().get(
            f"{at._device_kind()}|quant_matmul_blocks|{sig}")
    except Exception:
        cached = None
    if cached is not None and list(cached) in [list(c) for c in cands]:
        return int(cached[0]), int(cached[1]), bk
    if not at.enabled():
        return bm0, bn0, bk

    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
    qw = jnp.asarray(rng.integers(-127, 128, size=(k, n)), jnp.int8)
    sc = jnp.ones((n,), jnp.float32)

    def run(cand):
        jax.block_until_ready(
            _pallas_matmul(x, qw, sc, cand[0], cand[1], bk, False))

    try:
        bm, bn = at.autotune("quant_matmul_blocks", sig, cands, run)
        return int(bm), int(bn), bk
    except Exception:
        return bm0, bn0, bk


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def weight_only_matmul(x, qw, scale, bias=None, impl=None,
                       interpret=None):
    """``x @ dequant(qw, scale) [+ bias]`` without materializing the
    float weights: dequant fuses into the matmul at int8 read width.

    x [..., K] float (any leading dims), qw [K, N] int8, scale [N]
    float, bias [N] or None.  Accumulation is f32; the result is cast
    back to ``x.dtype`` before the bias add (matching the unfused
    ``x @ (q * s)`` path at f32, and bounding bf16 error by ONE final
    rounding).  ``impl``: None (auto: pallas on TPU, jnp twin
    elsewhere) | "jnp" | "pallas" | "pallas_interpret".
    """
    x = jnp.asarray(x)
    qw = jnp.asarray(qw)
    scale = jnp.asarray(scale)
    *lead, k = x.shape
    n = qw.shape[1]
    if qw.shape[0] != k:
        raise ValueError(
            f"weight_only_matmul: x K dim {k} != weight rows {qw.shape[0]}")
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    x2 = x.reshape(-1, k)
    if impl == "jnp":
        y = quant_matmul_jnp(x2, qw, scale)
    else:
        m = x2.shape[0]
        mp = _round_up(max(m, 1), _MIN_SUB)
        kp = _round_up(k, _LANE)
        npad = _round_up(n, _LANE)
        xp = x2 if (mp, kp) == (m, k) else jnp.pad(
            x2, ((0, mp - m), (0, kp - k)))
        wp = qw if (kp, npad) == (k, n) else jnp.pad(
            qw, ((0, kp - k), (0, npad - n)))
        sp = scale if npad == n else jnp.pad(scale, (0, npad - n))
        bm, bn, bk = pick_blocks(mp, kp, npad)
        y = _pallas_matmul(xp, wp, sp, bm, bn, bk,
                           interpret=(impl == "pallas_interpret"))
        y = y[:m, :n]
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + jnp.asarray(bias).astype(x.dtype)
    return y.reshape(*lead, n)

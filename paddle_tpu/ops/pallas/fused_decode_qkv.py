"""Fused decode-ingress Pallas kernel (ISSUE 18 tentpole, kernel 1/2):
norm (LayerNorm or RMSNorm) + fused-QKV projection + RoPE + paged-KV
append in ONE dispatch per decode layer.

Small-batch decode is dispatch-bound, not FLOP-bound (serving_bench r05:
paged_b1 82.6 tok/s vs dense 110.5 with launch_share attributing the gap
to ~10 launches per layer), so the whole token-ingress chain that
today runs as norm -> matmul -> (+bias) -> rope -> swap -> quantize ->
two/four page scatters collapses into a single ``pl.pallas_call``:

* the block math (``_qkv_block``) replays the EXACT op order of the
  unfused path — ``nn.functional.norm`` jnp moments, one fused or three
  separate ``jnp.matmul`` projections, ``models.llama.rope_angles``
  (the single home of the rope convention) with rotate-half — so fused
  and unfused activations are bitwise-identical, not just close;
* the paged-KV append reuses ``quantization.kv_quantize`` verbatim for
  int8 pools, so the bytes landing in the pools equal the unfused
  ``_slot_page_write`` path byte-for-byte;
* pools ride through ``memory_space=ANY`` refs aliased in-place
  (``input_output_aliases``), and each row's (page, slot) target —
  looked up from scalar-prefetched positions/block-tables, the
  block-tables-as-data contract that keeps serving recompile-free —
  is written with a small VMEM-staged ``make_async_copy``.

Following the PR4/PR7/PR11 fused-kernel discipline, the unjitted jnp
twin (``fused_decode_qkv_twin``) replays the identical row-block walk
(same padding, same block math, same per-row write order) so
Pallas-interpret parity is BITWISE on every geometry; the row block is
an autotune entry (``fused_decode_qkv_rows`` — ``pick_qkv_rows``).

Note: norm parity is vs the functional jnp norm (the decode bodies'
default everywhere, including TPU unless PDTPU_NORM_BACKEND=pallas
reroutes norms to the standalone fused-norm kernels).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rotate_half(x, cos, sin):
    """models.llama rope application (generation._apply_rope body)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


def _norm_block(xv, nw, nb, norm, eps):
    """The functional-layer norm math (nn/functional/norm.py `_moments`
    + apply order), shared by both decode megakernels and their twins.
    ``nw``/``nb`` arrive as [1, H]; bias applies ONLY when present
    (adding 0.0 would flip -0.0 -> +0.0 and break bitwise parity)."""
    v32 = xv.astype(jnp.float32)
    if norm == "layer":
        mean = jnp.mean(v32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(v32), axis=-1, keepdims=True) - \
            jnp.square(mean)
        out = (xv.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
        out = out.astype(xv.dtype)
    else:
        ms = jnp.mean(jnp.square(v32), axis=-1, keepdims=True)
        out = (v32 * jax.lax.rsqrt(ms + eps)).astype(xv.dtype)
    out = out * nw
    if nb is not None:
        out = out + nb
    return out


def _qkv_block(xv, posv, nw, nb, ws, bs, *, norm, eps, n_heads,
               n_kv_heads, head_dim, rope_theta):
    """One row-block of the fused ingress math: norm -> QKV projection
    (one fused [H, (q+2kv)] weight in GPT column order [3, nh, hd], or
    three separate llama weights) -> rope -> head-major K/V.  Returns
    (q [rows, nh, hd], k [hk, rows, hd], v [hk, rows, hd]).  Kernel and
    twin both call THIS function — parity is by construction."""
    rows = xv.shape[0]
    h = _norm_block(xv, nw, nb, norm, eps)
    nq, nk = n_heads * head_dim, n_kv_heads * head_dim
    if len(ws) == 1:
        qkv = jnp.matmul(h, ws[0])
        if bs:
            qkv = qkv + bs[0]
        # row-major column slices == reshape([rows, 3, nh, hd]) unbind
        q = qkv[:, :nq]
        k = qkv[:, nq:nq + nk]
        v = qkv[:, nq + nk:]
    else:
        q = jnp.matmul(h, ws[0])
        k = jnp.matmul(h, ws[1])
        v = jnp.matmul(h, ws[2])
        if bs:
            q = q + bs[0]
            k = k + bs[1]
            v = v + bs[2]
    q = q.reshape(rows, n_heads, head_dim)
    k = k.reshape(rows, n_kv_heads, head_dim)
    v = v.reshape(rows, n_kv_heads, head_dim)
    if rope_theta is not None:
        from ...models.llama import rope_angles
        cos, sin = rope_angles(posv.reshape(-1), head_dim, rope_theta)
        cos, sin = cos[:, None, :], sin[:, None, :]
        q = _rotate_half(q, cos, sin)
        k = _rotate_half(k, cos, sin)
    # head-major like the page pools (generation's swapaxes convention)
    return q, jnp.swapaxes(k, 0, 1), jnp.swapaxes(v, 0, 1)


def _quantize_or_cast(kt, vt, quant, k_dtype, v_dtype):
    """Pool bytes: ``quantization.kv_quantize`` verbatim (int8 pools) or
    the unfused path's plain ``.astype`` (fp/bf16 pools)."""
    if quant:
        from ...quantization import kv_quantize
        kq, ksc = kv_quantize(kt)
        vq, vsc = kv_quantize(vt)
        return kq, vq, ksc, vsc
    return kt.astype(k_dtype), vt.astype(v_dtype), None, None


def _page_slot(pos_s, bt_s, gr, page_size, npages):
    """(page, slot) for global row ``gr`` — generation._slot_page_write's
    lookup: clamp past-the-table positions onto the last page."""
    p = pos_s[gr]
    page = bt_s[gr, jnp.minimum(p // page_size, npages - 1)]
    return page, p % page_size


def _qkv_kernel(*refs, layout, cfg, rows, n_valid, quant):
    """Pallas body.  refs = 2 scalar-prefetch (positions, block tables)
    + regular inputs + outputs + scratch, unpacked per ``layout``."""
    (i_x, i_posv, i_nw, i_nb, i_ws, i_bs, i_kp, o_q, o_kp, o_vp,
     o_ks, o_vs, s_kb, s_vb, s_ksb, s_vsb, s_sem) = layout
    pos_s, bt_s = refs[0], refs[1]
    nb = refs[i_nb][...] if i_nb is not None else None
    q, kt, vt = _qkv_block(
        refs[i_x][...], refs[i_posv][...], refs[i_nw][...], nb,
        [refs[j][...] for j in i_ws], [refs[j][...] for j in i_bs],
        **cfg)
    refs[o_q][...] = q
    kq, vq, ksc, vsc = _quantize_or_cast(
        kt, vt, quant, refs[o_kp].dtype, refs[o_vp].dtype)
    page_size = refs[o_kp].shape[2]
    npages = bt_s.shape[1]
    base = pl.program_id(0) * rows
    sem = refs[s_sem]
    for r in range(rows):
        gr = base + r
        refs[s_kb][...] = kq[:, r:r + 1, :]
        refs[s_vb][...] = vq[:, r:r + 1, :]
        if quant:
            refs[s_ksb][...] = ksc[:, r:r + 1]
            refs[s_vsb][...] = vsc[:, r:r + 1]
        page, slot = _page_slot(pos_s, bt_s, gr, page_size, npages)
        copies = [(s_kb, o_kp), (s_vb, o_vp)]
        if quant:
            copies += [(s_ksb, o_ks), (s_vsb, o_vs)]

        def _write(copies=copies, page=page, slot=slot):
            for src, dst in copies:
                cp = pltpu.make_async_copy(
                    refs[src].at[...],
                    refs[dst].at[:, page, pl.ds(slot, 1)], sem)
                cp.start()
                cp.wait()

        pl.when(gr < n_valid)(_write)


def _prep(x, norm_w, norm_b, weights, biases, positions, block_tables,
          rows):
    """Shared wrapper/twin preamble: row-block size, padding, [1, H]
    param layouts.  The twin replays this verbatim."""
    b, h = x.shape
    rows_c = b if rows is None else int(rows)
    bp = ((b + rows_c - 1) // rows_c) * rows_c
    pad = bp - b
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        positions = jnp.pad(positions, (0, pad))
        block_tables = jnp.pad(block_tables, ((0, pad), (0, 0)))
    posp = positions.astype(jnp.int32)
    btp = block_tables.astype(jnp.int32)
    nw = norm_w.reshape(1, h)
    nb = norm_b.reshape(1, h) if norm_b is not None else None
    ws = [jnp.asarray(w) for w in weights]
    bs = [jnp.asarray(bi).reshape(1, -1) for bi in biases]
    return x, posp, btp, nw, nb, ws, bs, rows_c, bp


def fused_decode_qkv(x, norm_w, norm_b, weights, biases, positions,
                     block_tables, k_pages, v_pages, k_scales=None,
                     v_scales=None, *, norm="layer", eps=1e-5, n_heads,
                     n_kv_heads, head_dim, rope_theta=None, rows=None,
                     interpret=None):
    """Fused norm+QKV+rope+paged-append for one decode step.

    x: [B, H] token hidden states; weights: ONE fused [H, (nh+2*hk)*hd]
    projection (GPT column order [3, nh, hd]) or three separate
    (wq, wk, wv); biases: matching list or empty.  positions [B] i32,
    block_tables [B, NP] i32.  Pools are head-major [Hk, P, ps, D]
    (+ [Hk, P, ps] scale pools when quantized) and are updated
    IN-PLACE via input_output_aliases.  Returns
    (q [B, nh, hd], k_pages, v_pages[, k_scales, v_scales]).
    """
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    b, h = x.shape
    quant = k_scales is not None
    xp, posp, btp, nw, nb, ws, bs, rows_c, bp = _prep(
        x, norm_w, norm_b, weights, biases, positions, block_tables,
        rows)
    cfg = dict(norm=norm, eps=eps, n_heads=n_heads,
               n_kv_heads=n_kv_heads, head_dim=head_dim,
               rope_theta=rope_theta)
    q_abs, _, _ = jax.eval_shape(
        functools.partial(_qkv_block, **cfg),
        jax.ShapeDtypeStruct((rows_c, h), xp.dtype),
        jax.ShapeDtypeStruct((rows_c, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, h), nw.dtype),
        None if nb is None else jax.ShapeDtypeStruct((1, h), nb.dtype),
        [jax.ShapeDtypeStruct(w.shape, w.dtype) for w in ws],
        [jax.ShapeDtypeStruct(bi.shape, bi.dtype) for bi in bs])

    # regular-input layout (indices are into the kernel's full ref list:
    # 2 scalar-prefetch refs first, then inputs, outputs, scratch)
    row_spec = pl.BlockSpec((rows_c, h), lambda i, *_: (i, 0))
    one_spec = pl.BlockSpec((1, h), lambda i, *_: (0, 0))
    full = functools.partial(pl.BlockSpec,
                             index_map=lambda i, *_: (0, 0))
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    args = [xp, posp[:, None], nw]
    in_specs = [row_spec, pl.BlockSpec((rows_c, 1), lambda i, *_: (i, 0)),
                one_spec]
    i_x, i_posv, i_nw = 2, 3, 4
    i_nb = None
    if nb is not None:
        i_nb = 2 + len(args)
        args.append(nb)
        in_specs.append(one_spec)
    i_ws = []
    for w in ws:
        i_ws.append(2 + len(args))
        args.append(w)
        in_specs.append(full(w.shape))
    i_bs = []
    for bi in bs:
        i_bs.append(2 + len(args))
        args.append(bi)
        in_specs.append(full(bi.shape))
    i_kp = 2 + len(args)
    pools = [k_pages, v_pages] + ([k_scales, v_scales] if quant else [])
    args += pools
    in_specs += [any_spec] * len(pools)
    n_in = 2 + len(args)

    out_shape = [jax.ShapeDtypeStruct((bp, n_heads, head_dim),
                                      q_abs.dtype)]
    out_shape += [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in pools]
    out_specs = [pl.BlockSpec((rows_c, n_heads, head_dim),
                              lambda i, *_: (i, 0, 0))]
    out_specs += [any_spec] * len(pools)
    o_q = n_in
    o_kp, o_vp = n_in + 1, n_in + 2
    o_ks = n_in + 3 if quant else None
    o_vs = n_in + 4 if quant else None
    n_out = 1 + len(pools)

    scratch = [pltpu.VMEM((n_kv_heads, 1, head_dim), k_pages.dtype),
               pltpu.VMEM((n_kv_heads, 1, head_dim), v_pages.dtype)]
    s_kb, s_vb = n_in + n_out, n_in + n_out + 1
    s_ksb = s_vsb = None
    if quant:
        scratch += [pltpu.VMEM((n_kv_heads, 1), k_scales.dtype),
                    pltpu.VMEM((n_kv_heads, 1), v_scales.dtype)]
        s_ksb, s_vsb = s_vb + 1, s_vb + 2
    scratch.append(pltpu.SemaphoreType.DMA)
    s_sem = n_in + n_out + len(scratch) - 1

    layout = (i_x, i_posv, i_nw, i_nb, tuple(i_ws), tuple(i_bs), i_kp,
              o_q, o_kp, o_vp, o_ks, o_vs, s_kb, s_vb, s_ksb, s_vsb,
              s_sem)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(bp // rows_c,),
        in_specs=in_specs, out_specs=out_specs,
        scratch_shapes=scratch)
    aliases = {i_kp + j: 1 + j for j in range(len(pools))}
    outs = pl.pallas_call(
        functools.partial(_qkv_kernel, layout=layout, cfg=cfg,
                          rows=rows_c, n_valid=b, quant=quant),
        grid_spec=grid_spec, out_shape=out_shape,
        input_output_aliases=aliases, interpret=bool(interpret),
    )(posp, btp, *args)
    return (outs[0][:b],) + tuple(outs[1:])


def fused_decode_qkv_twin(x, norm_w, norm_b, weights, biases, positions,
                          block_tables, k_pages, v_pages, k_scales=None,
                          v_scales=None, *, norm="layer", eps=1e-5,
                          n_heads, n_kv_heads, head_dim, rope_theta=None,
                          rows=None, interpret=None):
    """jnp twin outside any pallas_call: replays the kernel's exact
    row-block walk — same padding, same ``_qkv_block`` math, same
    per-row quantize/cast and (page, slot) write order — so
    interpret-mode kernel output matches BITWISE on every geometry.
    The per-block math runs under ``jax.jit`` so both sides share
    XLA's elementwise-fusion (FMA) semantics — op-by-op eager
    execution drifts ~1 ulp on the norm scale/shift and rope chains.
    ``interpret`` accepted/ignored so the two functions are
    call-compatible."""
    del interpret
    b, h = x.shape
    quant = k_scales is not None
    xp, posp, btp, nw, nb, ws, bs, rows_c, bp = _prep(
        x, norm_w, norm_b, positions=positions,
        block_tables=block_tables, weights=weights, biases=biases,
        rows=rows)
    cfg = dict(norm=norm, eps=eps, n_heads=n_heads,
               n_kv_heads=n_kv_heads, head_dim=head_dim,
               rope_theta=rope_theta)
    blk = jax.jit(functools.partial(_qkv_block, **cfg))
    quantize = jax.jit(functools.partial(
        _quantize_or_cast, quant=quant, k_dtype=k_pages.dtype,
        v_dtype=v_pages.dtype))
    kp, vp, ks, vs = k_pages, v_pages, k_scales, v_scales
    page_size, npages = kp.shape[2], btp.shape[1]
    q_blocks = []
    for i in range(bp // rows_c):
        sl = slice(i * rows_c, (i + 1) * rows_c)
        q, kt, vt = blk(xp[sl], posp[sl, None], nw, nb, ws, bs)
        q_blocks.append(q)
        kq, vq, ksc, vsc = quantize(kt, vt)
        for r in range(rows_c):
            gr = i * rows_c + r
            if gr >= b:
                continue
            p = int(posp[gr])
            page = int(btp[gr, min(p // page_size, npages - 1)])
            slot = p % page_size
            kp = kp.at[:, page, slot].set(kq[:, r])
            vp = vp.at[:, page, slot].set(vq[:, r])
            if quant:
                ks = ks.at[:, page, slot].set(ksc[:, r])
                vs = vs.at[:, page, slot].set(vsc[:, r])
    q = jnp.concatenate(q_blocks, axis=0)[:b]
    return (q, kp, vp) + ((ks, vs) if quant else ())


# --------------------------------------------------------------------------
# autotune entry: fused_decode_qkv_rows
# --------------------------------------------------------------------------
_ROW_CANDIDATES = (4, 8, 16, 32, 64, 128)
_VMEM_CAP_BYTES = 4 * 1024 * 1024


def _row_candidates(b, hidden, width):
    """Row blocks whose activation tiles fit the VMEM cap (weights are
    resident regardless — the megakernel targets decode hidden sizes,
    not giant projection widths)."""
    cands = [c for c in _ROW_CANDIDATES if c <= max(b, 4)
             and c * (hidden + width) * 4 <= _VMEM_CAP_BYTES]
    return cands


def default_rows(b):
    """Whole batch in one block: decode batches are small and a single
    block keeps the matmul M-dim equal to the unfused path's."""
    return b


def pick_qkv_rows(b, hidden, n_heads, n_kv_heads, head_dim):
    """Row block for fused_decode_qkv through the autotune cache
    (entry ``fused_decode_qkv_rows``).  Cache hits apply everywhere;
    the measuring sweep runs on synthetic shapes only when autotuning
    is enabled, so a first serving call never stalls."""
    import numpy as np
    from . import autotune as at
    width = (n_heads + 2 * n_kv_heads) * head_dim
    cands = _row_candidates(b, hidden, width)
    fallback = default_rows(b)
    if len(cands) <= 1:
        return fallback
    sig = f"b{b}_h{hidden}_nh{n_heads}_hk{n_kv_heads}_d{head_dim}"
    try:
        cached = at._load_cache().get(
            f"{at._device_kind()}|fused_decode_qkv_rows|{sig}")
    except Exception:
        cached = None
    if cached is not None and cached in cands:
        return int(cached)
    if not at.enabled():
        return fallback

    rng = np.random.default_rng(0)
    npages, ps = 4, 8
    x = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    nw = jnp.ones((hidden,), jnp.float32)
    w = jnp.asarray(rng.normal(size=(hidden, width)) * 0.02, jnp.float32)
    pos = jnp.arange(b, dtype=jnp.int32)
    bt = jnp.arange(b * npages, dtype=jnp.int32).reshape(b, npages)
    pool = jnp.zeros((n_kv_heads, b * npages, ps, head_dim), jnp.float32)

    def run(cand):
        out = fused_decode_qkv(
            x, nw, None, [w], [], pos, bt, pool, pool,
            norm="rms", eps=1e-6, n_heads=n_heads,
            n_kv_heads=n_kv_heads, head_dim=head_dim, rows=int(cand))
        jax.block_until_ready(out)

    try:
        return int(at.autotune("fused_decode_qkv_rows", sig, cands, run))
    except Exception:
        return fallback

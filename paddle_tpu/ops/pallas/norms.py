"""Fused normalization Pallas kernels (rms_norm, layer_norm).

Capability analog of the reference fused-norm CUDA kernels
(``paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu``,
``fused_layernorm_kernel.cu``): one pass over the rows computes stats in
fp32 and applies scale/shift without materializing intermediates in HBM.
Backward recomputes the normalized value from saved fp32 stats (rstd/mean),
the standard fused-norm strategy.

Inputs are treated as [rows, hidden]: callers flatten leading dims. Weight
and bias (optional at the functional layer) are taken as required here —
the functional passes ones/zeros when absent, keeping the kernel mono-shape.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _row_block(rows):
    return min(256, rows)


def _pad_rows(x, br):
    pad = (-x.shape[0]) % br
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


# --------------------------------------------------------------------------
# rms_norm
# --------------------------------------------------------------------------
def _rms_fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dwp_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    wg = g * w
    # dx = rstd * (wg - xhat * mean(wg * xhat))
    c = jnp.mean(wg * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (wg - xhat * c)).astype(dx_ref.dtype)
    # partial dw, tile-aligned: an (8, h) block whose rows replicate the sum
    dwp_ref[0] = jnp.broadcast_to(
        jnp.sum(g * xhat, axis=0, keepdims=True), (8, xhat.shape[1]))


def _rms_call(x, w, eps, interpret):
    rows, h = x.shape
    br = _row_block(rows)
    xp = _pad_rows(x, br)
    grid = (xp.shape[0] // br,)
    o, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32)],
        interpret=interpret,
    )(xp, w[None, :])
    return o[:rows], rstd[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms2d(x, w, eps, interpret):
    return _rms_call(x, w, eps, interpret)[0]


def _rms2d_fwd(x, w, eps, interpret):
    o, rstd = _rms_call(x, w, eps, interpret)
    return o, (x, w, rstd)


def _rms2d_bwd(eps, interpret, res, g):
    x, w, rstd = res
    rows, h = x.shape
    br = _row_block(rows)
    xp = _pad_rows(x, br)
    gp = _pad_rows(g, br)
    rp = jnp.pad(rstd, ((0, xp.shape[0] - rows), (0, 0)))
    grid = (xp.shape[0] // br,)
    dx, dwp = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32)],
        interpret=interpret,
    )(xp, w[None, :], rp, gp)
    return dx[:rows], jnp.sum(dwp[:, 0], axis=0).astype(w.dtype)


_rms2d.defvjp(_rms2d_fwd, _rms2d_bwd)


def rms_norm(x, weight, eps=1e-6, interpret=None):
    """Fused RMSNorm over the last axis. x: [..., hidden]."""
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    shape = x.shape
    out = _rms2d(x.reshape(-1, shape[-1]), weight, float(eps),
                 bool(interpret))
    return out.reshape(shape)


# --------------------------------------------------------------------------
# layer_norm
# --------------------------------------------------------------------------
def _ln_fwd_kernel(x_ref, w_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    o_ref[:] = (xhat * w_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dwp_ref, dbp_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    wg = g * w
    c1 = jnp.mean(wg, axis=1, keepdims=True)
    c2 = jnp.mean(wg * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (wg - c1 - xhat * c2)).astype(dx_ref.dtype)
    h = xhat.shape[1]
    dwp_ref[0] = jnp.broadcast_to(
        jnp.sum(g * xhat, axis=0, keepdims=True), (8, h))
    dbp_ref[0] = jnp.broadcast_to(jnp.sum(g, axis=0, keepdims=True), (8, h))


def _ln_call(x, w, b, eps, interpret):
    rows, h = x.shape
    br = _row_block(rows)
    xp = _pad_rows(x, br)
    grid = (xp.shape[0] // br,)
    o, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32),
                   jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32)],
        interpret=interpret,
    )(xp, w[None, :], b[None, :])
    return o[:rows], mean[:rows], rstd[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln2d(x, w, b, eps, interpret):
    return _ln_call(x, w, b, eps, interpret)[0]


def _ln2d_fwd(x, w, b, eps, interpret):
    o, mean, rstd = _ln_call(x, w, b, eps, interpret)
    return o, (x, w, mean, rstd)


def _ln2d_bwd(eps, interpret, res, g):
    x, w, mean, rstd = res
    rows, h = x.shape
    br = _row_block(rows)
    xp = _pad_rows(x, br)
    gp = _pad_rows(g, br)
    pad = xp.shape[0] - rows
    mp = jnp.pad(mean, ((0, pad), (0, 0)))
    rp = jnp.pad(rstd, ((0, pad), (0, 0)))
    grid = (xp.shape[0] // br,)
    dx, dwp, dbp = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct(xp.shape, x.dtype),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32),
                   jax.ShapeDtypeStruct((grid[0], 8, h), jnp.float32)],
        interpret=interpret,
    )(xp, w[None, :], mp, rp, gp)
    return (dx[:rows], jnp.sum(dwp[:, 0], axis=0).astype(w.dtype),
            jnp.sum(dbp[:, 0], axis=0).astype(w.dtype))


_ln2d.defvjp(_ln2d_fwd, _ln2d_bwd)


def layer_norm(x, weight, bias, eps=1e-5, interpret=None):
    """Fused LayerNorm over the last axis. x: [..., hidden]."""
    if interpret is None:
        from . import use_interpret
        interpret = use_interpret()
    shape = x.shape
    out = _ln2d(x.reshape(-1, shape[-1]), weight, bias, float(eps),
                bool(interpret))
    return out.reshape(shape)

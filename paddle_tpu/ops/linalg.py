"""Linear algebra ops. Analog of ``python/paddle/tensor/linalg.py``
(reference ``linalg.py:176`` matmul) — matmuls stay large/batched so XLA can
tile them onto the MXU; bf16-friendly by default."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap, apply
from ..core.tensor import Tensor


@primitive
def _matmul(x, y, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return matmul(x, y)


@primitive
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@primitive
def mv(x, vec):
    return jnp.matmul(x, vec)


def einsum(equation, *operands):
    return apply("einsum", lambda *ops: jnp.einsum(equation, *ops), *operands)


@primitive
def _p_norm(x, p, axis, keepdim):
    if p == np.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf:
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum(jnp.asarray(x != 0, x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@primitive
def _fro_norm(x, axis, keepdim):
    return jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdim))


def norm(x, p=None, axis=None, keepdim=False):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    if p is None or p == "fro":
        return _fro_norm(x, axis=axis, keepdim=keepdim)
    return _p_norm(x, p=float(p), axis=axis, keepdim=keepdim)


@primitive
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@primitive
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@primitive
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@primitive
def inverse(x):
    return jnp.linalg.inv(x)


inv = inverse


@primitive
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@primitive
def solve(x, y):
    return jnp.linalg.solve(x, y)


@primitive
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@primitive
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@primitive
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@primitive
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@primitive
def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eig(x):
    # general eig has no XLA lowering on TPU: host fallback (eager only)
    arr = np.asarray(unwrap(x))
    w, v = np.linalg.eig(arr)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


@primitive
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@primitive
def det(x):
    return jnp.linalg.det(x)


@primitive
def slogdet(x):
    s, ld = jnp.linalg.slogdet(x)
    return jnp.stack([s, ld])


@primitive
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@primitive
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@primitive
def multi_dot(xs):
    return jnp.linalg.multi_dot(xs)


@primitive
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@primitive
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@primitive
def bincount_weighted(x, w):
    return jnp.bincount(x, weights=w)


@primitive
def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based


@primitive
def householder_product(x, tau):
    return jax.lax.linalg.householder_product(x, tau)


@primitive
def cond(x, p=None):
    """Reference ``linalg.cond``: condition number (default 2-norm)."""
    return jnp.linalg.cond(x, p=p)


@primitive
def matrix_exp(x):
    """Reference ``linalg.matrix_exp``."""
    return jax.scipy.linalg.expm(x)


@primitive
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    """Reference ``linalg.matrix_norm``: norms over the trailing matrix
    dims ('fro', 'nuc', 1, -1, 2, -2, inf, -inf)."""
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis),
                           keepdims=keepdim)


@primitive
def vector_norm(x, p=2.0, axis=None, keepdim=False):
    """Reference ``linalg.vector_norm``: p-norm over ``axis`` (all dims
    when None; keepdim then yields an all-ones shape of x's rank)."""
    if axis is None:
        out = jnp.linalg.norm(x.reshape(-1), ord=p, axis=0)
        return out.reshape((1,) * x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference ``linalg.pca_lowrank``: rank-q PCA factors (U, S, V).
    Exact thin SVD of the (optionally centered) matrix — on TPU the full
    matmul-based SVD is the efficient path; ``niter`` (the randomized
    power-iteration count) is accepted for signature parity."""
    from ..core.dispatch import apply

    m, n = x.shape[-2], x.shape[-1]
    k = min(6, m, n) if q is None else q
    if not 0 < k <= min(m, n):
        raise ValueError(f"pca_lowrank: q={k} must be in (0, "
                         f"min(m, n)={min(m, n)}]")

    def impl(v):
        vv = v - v.mean(axis=-2, keepdims=True) if center else v
        u, s, vt = jnp.linalg.svd(vv, full_matrices=False)
        return (u[..., :, :k], s[..., :k],
                jnp.swapaxes(vt, -1, -2)[..., :, :k])

    return apply("pca_lowrank", impl, x)


# linalg namespace aliases (implementations in ops/special.py)
from .special import eigvals, lu_unpack  # noqa: F401,E402

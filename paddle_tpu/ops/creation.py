"""Tensor creation ops. Analog of ``python/paddle/tensor/creation.py``
(reference) over jnp; kernels are XLA's (SURVEY C11 creation kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, to_tensor  # re-export
from ..core.dispatch import primitive, unwrap


def _dt(dtype):
    d = convert_dtype(dtype)
    return state.DEFAULT_DTYPE if d is None else d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._read()))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) if not isinstance(s, int) else s for s in shape)


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None):
    fill_value = unwrap(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int)):
        dtype = "bool" if isinstance(fill_value, bool) else "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


@primitive
def _zeros_like(x, dtype):
    return jnp.zeros(x.shape, dtype or x.dtype)


def zeros_like(x, dtype=None):
    return _zeros_like(x, dtype=convert_dtype(dtype))


def ones_like(x, dtype=None):
    x = x._read() if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.ones(x.shape, convert_dtype(dtype) or x.dtype))


def full_like(x, fill_value, dtype=None):
    x = x._read() if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.full(x.shape, unwrap(fill_value),
                           convert_dtype(dtype) or x.dtype))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            dtype = state.DEFAULT_DTYPE
        else:
            dtype = np.dtype("int64")
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@primitive
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


@primitive
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@primitive
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    return jnp.vectorize(jnp.diag, signature="(n)->(n,n)")(x) if (
        offset == 0 and dim1 == -2 and dim2 == -1) else _diag_embed_general(
            x, offset, dim1, dim2)


def _diag_embed_general(x, offset, dim1, dim2):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = out.at[..., r, c].set(x)
    src = list(range(out.ndim))
    d1 = dim1 % out.ndim
    d2 = dim2 % out.ndim
    if (d1, d2) != (out.ndim - 2, out.ndim - 1):
        perm = [d for d in src if d not in (out.ndim - 2, out.ndim - 1)]
        perm.insert(d1, out.ndim - 2)
        perm.insert(d2, out.ndim - 1)
        out = jnp.transpose(out, perm)
    return out


@primitive
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@primitive
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]))


def triu_indices(row, col=None, offset=0):
    r, c = jnp.triu_indices(row, k=offset, m=col or row)
    return Tensor(jnp.stack([r, c]))


def meshgrid(*args):
    args = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[unwrap(a) for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


@primitive
def assign(x):
    return jnp.asarray(x)


def clone(x):
    return assign(x)


def one_hot(x, num_classes):
    x = unwrap(x)
    return Tensor(jax.nn.one_hot(x, num_classes, dtype=state.DEFAULT_DTYPE))


@primitive
def complex(real, imag):
    return jax.lax.complex(real, imag)


@primitive
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@primitive
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@primitive
def real(x):
    return jnp.real(x)


@primitive
def imag(x):
    return jnp.imag(x)


@primitive
def polar(abs, angle):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


def numel(x):
    x = unwrap(x)
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1,
                              dtype=jnp.int64))

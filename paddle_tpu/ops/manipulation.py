"""Shape/layout/indexing ops. Analog of
``python/paddle/tensor/manipulation.py`` (reference). XLA makes most of these
free (layout/copy elision), unlike the reference's stride-kernel machinery
(SURVEY C8 strides)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap, apply
from ..core.tensor import Tensor


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._read()))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


@primitive
def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape):
    return _reshape(x, shape=_norm_shape(shape))


def reshape_(x, shape):
    out = reshape(x, shape)
    x._adopt(out)
    return x


def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply("view_dtype", lambda v: v.view(shape_or_dtype), x)


@primitive
def _transpose(x, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm=None):
    if perm is not None:
        perm = tuple(int(p) for p in perm)
    return _transpose(x, perm=perm)


def transpose_last2(x):
    nd = x.ndim
    if nd < 2:
        return transpose(x)
    perm = tuple(range(nd - 2)) + (nd - 1, nd - 2)
    return transpose(x, perm)


def t(x):
    if x.ndim <= 1:
        return x
    return transpose(x, (1, 0))


@primitive
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@primitive
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@primitive
def _flatten(x, start_axis, stop_axis):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    start = start_axis % nd
    stop = stop_axis % nd
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return x.reshape(shape)


def flatten(x, start_axis=0, stop_axis=-1):
    return _flatten(x, start_axis=start_axis, stop_axis=stop_axis)


@primitive
def _squeeze(x, axis):
    if axis is None:
        return jnp.squeeze(x)
    axis = [axis] if isinstance(axis, int) else list(axis)
    axis = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


def squeeze(x, axis=None):
    return _squeeze(x, axis=axis)


@primitive
def _unsqueeze(x, axis):
    axis = [axis] if isinstance(axis, int) else list(axis)
    out = x
    nd = x.ndim + len(axis)
    for a in sorted(a % nd for a in axis):
        out = jnp.expand_dims(out, a)
    return out


def unsqueeze(x, axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    return _unsqueeze(x, axis=axis)


def unsqueeze_(x, axis):
    out = unsqueeze(x, axis)
    x._adopt(out)
    return x


@primitive
def _concat(xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(list(x), axis=axis)


@primitive
def _stack(xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0):
    return _stack(list(x), axis=axis)


def split(x, num_or_sections, axis=0):
    axis = int(unwrap(axis))
    dim = x.shape[axis % x.ndim]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis dim {dim} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(unwrap(s)) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - builtins.sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def fn(v):
        return tuple(
            jax.lax.slice_in_dim(v, o, o + s, axis=axis % v.ndim)
            for o, s in zip(offsets, sizes))

    return list(apply("split", fn, x))


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    axis = axis % x.ndim
    n = x.shape[axis]

    def fn(v):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(v, i, i + 1, axis=axis),
                                 axis=axis) for i in range(n))

    return list(apply("unbind", fn, x))


def unstack(x, axis=0, num=None):
    return unbind(x, axis)


@primitive
def _tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return _tile(x, repeat_times=tuple(int(unwrap(r)) for r in repeat_times))


@primitive
def _broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def broadcast_to(x, shape):
    return _broadcast_to(x, shape=_norm_shape(shape))


def expand(x, shape):
    shape = _norm_shape(shape)
    # paddle expand: -1 keeps original dim
    xs = list(x.shape)
    full = []
    pad = len(shape) - len(xs)
    for i, s in enumerate(shape):
        if s == -1:
            full.append(xs[i - pad] if i >= pad else 1)
        else:
            full.append(s)
    return broadcast_to(x, full)


def expand_as(x, y):
    return broadcast_to(x, y.shape)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [broadcast_to(t, out_shape) for t in inputs]


@primitive
def _flip(x, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return _flip(x, axis=tuple(axis))


def rot90(x, k=1, axes=(0, 1)):
    return apply("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


@primitive
def _roll(x, shifts, axis):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None):
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    return _roll(x, shifts=shifts, axis=axis)


@primitive
def cast(x, dtype):
    return x.astype(dtype)


def astype(x, dtype):
    from ..core.dtype import convert_dtype
    return cast(x, dtype=convert_dtype(dtype))


@primitive
def _pad_nd(x, pad, mode, value):
    if mode == "constant":
        return jnp.pad(x, pad, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, pad, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """paddle.nn.functional.pad-compatible: `pad` is [l,r] pairs from the
    LAST axis backward when len(pad) < 2*ndim (torch-style), or per-axis."""
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(unwrap(p)) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # torch/paddle semantics: FIRST pair pads the LAST (innermost) axis,
        # working backward; for channel-last layouts the innermost padded
        # axis sits just before the trailing channel dim.
        npairs = len(pad) // 2
        width = [(0, 0)] * nd
        last = nd - 1
        if data_format in ("NHWC", "NLC", "NDHWC"):
            last = nd - 2
        for i in range(npairs):
            width[last - i] = (pad[2 * i], pad[2 * i + 1])
    return _pad_nd(x, pad=tuple(width), mode=mode, value=value)


@primitive
def _slice(x, axes, starts, ends):
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = builtins.max(st + dim, 0) if st < 0 else builtins.min(st, dim)
        en = builtins.max(en + dim, 0) if en < 0 else builtins.min(en, dim)
        x = jax.lax.slice_in_dim(x, st, builtins.max(en, st), axis=ax)
    return x


def slice(x, axes, starts, ends):
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]
    return _slice(x, axes=tuple(axes), starts=tuple(starts), ends=tuple(ends))


@primitive
def _strided_slice(x, axes, starts, ends, strides):
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(st, en, sd)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    return _strided_slice(
        x, axes=tuple(axes), starts=tuple(int(unwrap(s)) for s in starts),
        ends=tuple(int(unwrap(e)) for e in ends),
        strides=tuple(int(unwrap(s)) for s in strides))


# ---- gather/scatter family ----------------------------------------------


@primitive
def gather(x, index, axis=0):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


@primitive
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@primitive
def take_along_axis(x, indices, axis, broadcast=True):
    if broadcast:
        # broadcast indices against x on every dim EXCEPT axis (reference
        # take_along_axis broadcast semantics)
        xs = list(x.shape)
        xs[axis] = 1
        ishape = list(indices.shape)
        ishape[axis] = 1
        shape = list(jnp.broadcast_shapes(tuple(xs), tuple(ishape)))
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, shape)
    return jnp.take_along_axis(x, indices, axis=axis)


@primitive
def put_along_axis(x, indices, values, axis, reduce="assign"):
    values = jnp.broadcast_to(jnp.asarray(values, x.dtype), indices.shape)
    return _pala(x, indices, values, axis,
                 "set" if reduce == "assign" else reduce)


def _pala(x, indices, values, axis, mode):
    dims = list(range(x.ndim))
    ind = [jnp.broadcast_to(
        jnp.arange(x.shape[d]).reshape([-1 if i == d else 1 for i in dims]),
        indices.shape) for d in dims]
    ind[axis] = indices
    at = x.at[tuple(ind)]
    if mode == "set":
        return at.set(values)
    if mode in ("add", "sum"):
        return at.add(values)
    if mode in ("mul", "multiply"):
        return at.multiply(values)
    raise ValueError(f"unsupported reduce mode {mode}")


@primitive
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@primitive
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@primitive
def index_add(x, index, axis, value):
    idx = [builtins.slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@primitive
def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


@primitive
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@primitive
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    index, updates = unwrap(index), unwrap(updates)
    zeros = jnp.zeros(_norm_shape(shape), updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return Tensor(zeros.at[idx].add(updates))


@primitive
def masked_select(x, mask):
    # dynamic-shape op: eager only (XLA needs static shapes under jit)
    return x[mask]


@primitive
def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@primitive
def masked_scatter(x, mask, value):
    n = int(mask.sum())
    return x.at[mask].set(value.reshape(-1)[:n])


@primitive
def where(condition, x, y):
    return jnp.where(condition, x, y)


@primitive
def select_scatter(x, values, axis, index):
    idx = [builtins.slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@primitive
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


# ---- search / sort -------------------------------------------------------


@primitive
def topk(x, k, axis=-1, largest=True, sorted=True):
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
        v, i = jax.lax.top_k(xm if largest else -xm, k)
        v = v if largest else -v
        return (jnp.moveaxis(v, -1, axis),
                jnp.moveaxis(i.astype(jnp.int64), -1, axis))
    v, i = jax.lax.top_k(x if largest else -x, k)
    return (v if largest else -v), i.astype(jnp.int64)


@primitive
def sort(x, axis=-1, descending=False, stable=False):
    out = jnp.sort(x, axis=axis, stable=stable)
    return jnp.flip(out, axis=axis) if descending else out


@primitive
def argsort(x, axis=-1, descending=False, stable=False):
    out = jnp.argsort(x, axis=axis, stable=stable).astype(jnp.int64)
    return jnp.flip(out, axis=axis) if descending else out


@primitive
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    r = jnp.searchsorted(sorted_sequence, values, side=side)
    return r.astype(jnp.int32 if out_int32 else jnp.int64)


@primitive
def kthvalue(x, k, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    i = jnp.argsort(x, axis=axis).astype(jnp.int64)
    sl = [builtins.slice(None)] * x.ndim
    sl[axis] = builtins.slice(k - 1, k)
    v, i = v[tuple(sl)], i[tuple(sl)]
    if not keepdim:
        v, i = jnp.squeeze(v, axis), jnp.squeeze(i, axis)
    return v, i


@primitive
def mode(x, axis=-1, keepdim=False):
    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    # most-frequent value: O(n^2) pairwise count (fine for op-sized n),
    # ties resolved to the smallest value (argmax over sorted order)
    s = jnp.sort(xm, axis=-1)
    counts = jnp.sum(s[..., :, None] == s[..., None, :], axis=-1)
    pick = jnp.argmax(counts, axis=-1, keepdims=True)
    out = jnp.take_along_axis(s, pick, axis=-1)
    # index of the LAST occurrence (reference/torch mode convention)
    n = xm.shape[-1]
    idx = n - 1 - jnp.argmax(
        jnp.asarray(xm == out, jnp.int32)[..., ::-1], axis=-1, keepdims=True)
    out = jnp.moveaxis(out, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if not keepdim:
        out, idx = jnp.squeeze(out, axis), jnp.squeeze(idx, axis)
    return out, idx.astype(jnp.int64)


def nonzero(x, as_tuple=False):
    # dynamic output shape: eager-only
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(z, jnp.int64)) for z in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=-1), jnp.int64))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(unwrap(x))
    flat = arr.flatten() if axis is None else arr
    keep = np.ones(flat.shape[0 if axis is None else axis], bool)
    if axis is None:
        keep[1:] = flat[1:] != flat[:-1]
        out = flat[keep]
    else:
        sl = np.any(np.diff(flat, axis=axis) != 0,
                    axis=tuple(i for i in range(flat.ndim) if i != axis))
        keep[1:] = sl
        out = np.compress(keep, flat, axis=axis)
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1, np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, keep.shape[0]))
        outs.append(Tensor(jnp.asarray(counts, np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


@primitive
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


@primitive
def histogram(x, bins=100, min=0, max=0):
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(x, bins=bins, range=rng)
    return h


def shape(x):
    return Tensor(jnp.asarray(x.shape, jnp.int32))


@primitive
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    lo, hi = shard_id * size, (shard_id + 1) * size
    inside = (x >= lo) & (x < hi)
    return jnp.where(inside, x - lo, ignore_value)


def tensordot(x, y, axes=2):
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def as_strided(x, shape, stride, offset=0):
    def fn(v):
        flat = v.reshape(-1)[offset:]
        idx = np.zeros(tuple(shape), np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            idx = idx + r.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[idx.reshape(-1)].reshape(tuple(shape))
    return apply("as_strided", fn, x)

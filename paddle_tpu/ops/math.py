"""Elementwise & reduction math ops.

Analog of ``python/paddle/tensor/math.py`` (reference; e.g. ``add``, ``scale``)
with kernels delegated to XLA (SURVEY C11 ``paddle/phi/kernels/``; the
broadcast/elementwise machinery of ``kernels/funcs/broadcast_function.h``
is jnp broadcasting)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor

# ---- binary elementwise --------------------------------------------------


@primitive
def add(x, y):
    return jnp.add(x, y)


@primitive
def subtract(x, y):
    return jnp.subtract(x, y)


@primitive
def multiply(x, y):
    return jnp.multiply(x, y)


@primitive
def divide(x, y):
    return jnp.true_divide(x, y)


@primitive
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@primitive
def mod(x, y):
    return jnp.mod(x, y)


remainder = mod


@primitive
def pow(x, y):
    return jnp.power(x, y)


@primitive
def maximum(x, y):
    return jnp.maximum(x, y)


@primitive
def minimum(x, y):
    return jnp.minimum(x, y)


@primitive
def fmax(x, y):
    return jnp.fmax(x, y)


@primitive
def fmin(x, y):
    return jnp.fmin(x, y)


@primitive
def atan2(x, y):
    return jnp.arctan2(x, y)


@primitive
def hypot(x, y):
    return jnp.hypot(x, y)


@primitive
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@primitive
def nextafter(x, y):
    return jnp.nextafter(x, y)


@primitive
def copysign(x, y):
    return jnp.copysign(x, y)


@primitive
def gcd(x, y):
    return jnp.gcd(x, y)


@primitive
def lcm(x, y):
    return jnp.lcm(x, y)


@primitive
def heaviside(x, y):
    return jnp.heaviside(x, y)


@primitive
def lerp(x, y, weight):
    return x + weight * (y - x)


# ---- unary elementwise ---------------------------------------------------


@primitive
def sqrt(x):
    return jnp.sqrt(x)


@primitive
def rsqrt(x):
    return jax.lax.rsqrt(x)


@primitive
def exp(x):
    return jnp.exp(x)


@primitive
def expm1(x):
    return jnp.expm1(x)


@primitive
def log(x):
    return jnp.log(x)


@primitive
def log2(x):
    return jnp.log2(x)


@primitive
def log10(x):
    return jnp.log10(x)


@primitive
def log1p(x):
    return jnp.log1p(x)


@primitive
def abs(x):
    return jnp.abs(x)


@primitive
def neg(x):
    return jnp.negative(x)


@primitive
def sign(x):
    return jnp.sign(x)


@primitive
def floor(x):
    return jnp.floor(x)


@primitive
def ceil(x):
    return jnp.ceil(x)


@primitive
def round(x):
    return jnp.round(x)


@primitive
def trunc(x):
    return jnp.trunc(x)


@primitive
def frac(x):
    return x - jnp.trunc(x)


@primitive
def sin(x):
    return jnp.sin(x)


@primitive
def cos(x):
    return jnp.cos(x)


@primitive
def tan(x):
    return jnp.tan(x)


@primitive
def asin(x):
    return jnp.arcsin(x)


@primitive
def acos(x):
    return jnp.arccos(x)


@primitive
def atan(x):
    return jnp.arctan(x)


@primitive
def sinh(x):
    return jnp.sinh(x)


@primitive
def cosh(x):
    return jnp.cosh(x)


@primitive
def tanh(x):
    return jnp.tanh(x)


@primitive
def asinh(x):
    return jnp.arcsinh(x)


@primitive
def acosh(x):
    return jnp.arccosh(x)


@primitive
def atanh(x):
    return jnp.arctanh(x)


@primitive
def erf(x):
    return jax.lax.erf(x)


@primitive
def erfinv(x):
    return jax.lax.erf_inv(x)


@primitive
def reciprocal(x):
    return jnp.reciprocal(x)


@primitive
def square(x):
    return jnp.square(x)


@primitive
def rad2deg(x):
    return jnp.rad2deg(x)


@primitive
def deg2rad(x):
    return jnp.deg2rad(x)


@primitive
def digamma(x):
    return jax.scipy.special.digamma(x)


@primitive
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@primitive
def i0(x):
    return jax.scipy.special.i0(x)


@primitive
def i0e(x):
    return jax.scipy.special.i0e(x)


@primitive
def i1(x):
    return jax.scipy.special.i1(x)


@primitive
def i1e(x):
    return jax.scipy.special.i1e(x)


@primitive
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    s = jnp.asarray(scale, x.dtype) if not hasattr(scale, "dtype") else scale.astype(x.dtype)
    b = jnp.asarray(bias, x.dtype)
    if bias_after_scale:
        return x * s + b
    return (x + b) * s


@primitive
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@primitive
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@primitive
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


# ---- scan / cumulative ---------------------------------------------------


@primitive
def cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@primitive
def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def _running_extreme(x, axis, op):
    """Running max/min values + index where the current extreme was attained
    (last attaining position, via an associative scan over masked indices)."""
    vals = jax.lax.associative_scan(op, x, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    idx = jnp.broadcast_to(
        jnp.arange(x.shape[axis], dtype=jnp.int64).reshape(shape), x.shape)
    attained = jnp.where(x == vals, idx, jnp.int64(-1))
    inds = jax.lax.associative_scan(jnp.maximum, attained, axis=axis)
    return vals, inds


@primitive
def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _running_extreme(x, axis % x.ndim, jnp.maximum)


@primitive
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _running_extreme(x, axis % x.ndim, jnp.minimum)


@primitive
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


# ---- reductions ----------------------------------------------------------


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@primitive
def _sum(x, axis, keepdim, dtype):
    return jnp.sum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def sum(x, axis=None, dtype=None, keepdim=False):
    return _sum(x, axis=_axis(axis), keepdim=keepdim, dtype=dtype)


@primitive
def _mean(x, axis, keepdim):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return _mean(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _max(x, axis, keepdim):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    return _max(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _min(x, axis, keepdim):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    return _min(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _prod(x, axis, keepdim, dtype):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def prod(x, axis=None, keepdim=False, dtype=None):
    return _prod(x, axis=_axis(axis), keepdim=keepdim, dtype=dtype)


@primitive
def amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=axis, keepdims=keepdim)


@primitive
def amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=axis, keepdims=keepdim)


@primitive
def _std(x, axis, unbiased, keepdim):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return _std(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@primitive
def _var(x, axis, unbiased, keepdim):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return _var(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@primitive
def _logsumexp(x, axis, keepdim):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return _logsumexp(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _median(x, axis, keepdim):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return _median(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _quantile(x, q, axis, keepdim):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return _quantile(x, unwrap(q), axis=_axis(axis), keepdim=keepdim)


@primitive
def _nanmean(x, axis, keepdim):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return _nanmean(x, axis=_axis(axis), keepdim=keepdim)


@primitive
def _nansum(x, axis, keepdim, dtype):
    return jnp.nansum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def nansum(x, axis=None, dtype=None, keepdim=False):
    return _nansum(x, axis=_axis(axis), keepdim=keepdim, dtype=dtype)


def count_nonzero(x, axis=None, keepdim=False):
    x = unwrap(x)
    return Tensor(jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim))


@primitive
def _argmax(x, axis, keepdim, dtype):
    r = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return r.astype(dtype or jnp.int64)


def argmax(x, axis=None, keepdim=False, dtype=None):
    return _argmax(x, axis=None if axis is None else int(axis),
                   keepdim=keepdim, dtype=dtype)


@primitive
def _argmin(x, axis, keepdim, dtype):
    r = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return r.astype(dtype or jnp.int64)


def argmin(x, axis=None, keepdim=False, dtype=None):
    return _argmin(x, axis=None if axis is None else int(axis),
                   keepdim=keepdim, dtype=dtype)


# ---- predicates ----------------------------------------------------------


@primitive
def isnan(x):
    return jnp.isnan(x)


@primitive
def isinf(x):
    return jnp.isinf(x)


@primitive
def isfinite(x):
    return jnp.isfinite(x)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return Tensor(jnp.isclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return Tensor(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


# ---- misc ----------------------------------------------------------------


@primitive
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@primitive
def inner(x, y):
    return jnp.inner(x, y)


@primitive
def outer(x, y):
    return jnp.outer(x, y)


@primitive
def kron(x, y):
    return jnp.kron(x, y)


@primitive
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@primitive
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def increment(x, value=1.0):
    out = add(x, Tensor(jnp.asarray(value, x.dtype)))
    x._adopt(out)
    return x


@primitive
def angle(x):
    return jnp.angle(x)


@primitive
def conj(x):
    return jnp.conj(x)

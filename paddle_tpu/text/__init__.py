"""``paddle.text`` parity — text datasets + decode ops.

Analog of ``python/paddle/text/`` (datasets: Imdb/Conll05/...) and the
sequence-decode ops ``viterbi_decode`` (``paddle/phi/kernels/
viterbi_decode_kernel.h``) and ``gather_tree`` (beam-search trace-back).
Datasets ship as synthetic-capable loaders: the reference downloads
corpora; in an air-gapped image we generate deterministic corpora with
identical structure (document in each class docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..io import Dataset


# decode ops: single implementations live in ops/special.py (reference
# text/viterbi_decode.py convention: last transition row = start tag,
# second-to-last column = stop tag)
from ..ops.special import gather_tree, viterbi_decode  # noqa: F401


class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic corpus with the reference dataset's
    (tokens, label) structure — documented stand-in for the downloadable
    corpora (zero-egress image)."""

    num_classes = 2
    vocab_size = 1000

    def __init__(self, mode="train", n=256, seq_len=64, seed=0):
        rng = np.random.default_rng(
            seed + (0 if mode == "train" else 1))
        self.labels = rng.integers(0, self.num_classes,
                                   n).astype("int64")
        # class-conditional unigram skew so models can actually learn
        base = rng.random((self.num_classes, self.vocab_size))
        base = base / base.sum(-1, keepdims=True)
        self.tokens = np.stack([
            rng.choice(self.vocab_size, seq_len, p=base[c])
            for c in self.labels]).astype("int64")

    def __getitem__(self, i):
        return self.tokens[i], np.asarray([self.labels[i]], "int64")

    def __len__(self):
        return len(self.labels)


class Imdb(_SyntheticTextDataset):
    """Reference ``text/datasets/imdb.py`` structure (binary sentiment)."""


class Imikolov(_SyntheticTextDataset):
    """Reference ``text/datasets/imikolov.py`` (LM ngrams)."""

    def __getitem__(self, i):
        toks = self.tokens[i]
        return toks[:-1], toks[1:]


__all__ = ["gather_tree", "viterbi_decode", "Imdb", "Imikolov"]

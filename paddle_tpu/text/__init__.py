"""``paddle.text`` parity — text datasets + decode ops.

Analog of ``python/paddle/text/`` (datasets: Imdb/Conll05/...) and the
sequence-decode ops ``viterbi_decode`` (``paddle/phi/kernels/
viterbi_decode_kernel.h``) and ``gather_tree`` (beam-search trace-back).
Datasets ship as synthetic-capable loaders: the reference downloads
corpora; in an air-gapped image we generate deterministic corpora with
identical structure (document in each class docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..io import Dataset


# decode ops: single implementations live in ops/special.py (reference
# text/viterbi_decode.py convention: last transition row = start tag,
# second-to-last column = stop tag)
from ..ops.special import gather_tree, viterbi_decode  # noqa: F401


class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic corpus with the reference dataset's
    (tokens, label) structure — documented stand-in for the downloadable
    corpora (zero-egress image)."""

    num_classes = 2
    vocab_size = 1000

    def __init__(self, mode="train", n=256, seq_len=64, seed=0):
        rng = np.random.default_rng(
            seed + (0 if mode == "train" else 1))
        self.labels = rng.integers(0, self.num_classes,
                                   n).astype("int64")
        # class-conditional unigram skew so models can actually learn
        base = rng.random((self.num_classes, self.vocab_size))
        base = base / base.sum(-1, keepdims=True)
        self.tokens = np.stack([
            rng.choice(self.vocab_size, seq_len, p=base[c])
            for c in self.labels]).astype("int64")

    def __getitem__(self, i):
        return self.tokens[i], np.asarray([self.labels[i]], "int64")

    def __len__(self):
        return len(self.labels)


class Imdb(_SyntheticTextDataset):
    """Reference ``text/datasets/imdb.py`` structure (binary sentiment)."""


class Imikolov(_SyntheticTextDataset):
    """Reference ``text/datasets/imikolov.py`` (LM ngrams)."""

    def __getitem__(self, i):
        toks = self.tokens[i]
        return toks[:-1], toks[1:]


class UCIHousing(Dataset):
    """Reference ``text/datasets/uci_housing.py:42``: items are
    (features [13] f32, target [1] f32). Reads the standard whitespace
    ``housing.data`` file when given, else a deterministic synthetic
    regression with the same shapes (zero-egress image)."""

    FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        if data_file is not None:
            raw = np.loadtxt(data_file, dtype=np.float32)
        else:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(506, self.FEATURES)).astype("float32")
            w = rng.normal(size=(self.FEATURES,)).astype("float32")
            y = (x @ w + 0.1 * rng.normal(size=506)).astype("float32")
            raw = np.concatenate([x, y[:, None]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1].astype("float32"), row[-1:].astype("float32")

    def __len__(self):
        return len(self.data)


class Conll05st(_SyntheticTextDataset):
    """Reference ``text/datasets/conll05.py:39`` (SRL): items are
    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark,
    label_ids) — synthetic with the reference's 9-field structure."""

    num_labels = 67

    def __getitem__(self, i):
        toks = self.tokens[i]
        ctx = [np.roll(toks, k) for k in (2, 1, 0, -1, -2)]
        pred = np.full_like(toks, int(self.labels[i]))
        mark = (toks % 7 == 0).astype("int64")
        lab = (toks % self.num_labels).astype("int64")
        return (toks, *ctx, pred, mark, lab)


class Movielens(Dataset):
    """Reference ``text/datasets/movielens.py``: items are
    (user_id, gender, age, job, movie_id, category-multi-hot-ish title
    ids, rating [1] f32) — synthetic with the same field layout."""

    def __init__(self, data_file=None, mode="train", n=512, seed=0):
        rng = np.random.default_rng(seed + (mode != "train"))
        self.user = rng.integers(1, 6041, n)
        self.gender = rng.integers(0, 2, n)
        self.age = rng.integers(0, 7, n)
        self.job = rng.integers(0, 21, n)
        self.movie = rng.integers(1, 3953, n)
        self.title = rng.integers(0, 5175, (n, 8))
        self.rating = rng.integers(1, 6, n).astype("float32")

    def __getitem__(self, i):
        return (np.int64(self.user[i]), np.int64(self.gender[i]),
                np.int64(self.age[i]), np.int64(self.job[i]),
                np.int64(self.movie[i]), self.title[i].astype("int64"),
                np.asarray([self.rating[i]], "float32"))

    def __len__(self):
        return len(self.user)


class _WMT(_SyntheticTextDataset):
    """Shared structure of wmt14/wmt16 (reference
    ``text/datasets/wmt14.py``/``wmt16.py``): items are
    (src_ids, trg_ids, trg_ids_next) for seq2seq training."""

    def __getitem__(self, i):
        toks = self.tokens[i]
        half = len(toks) // 2
        src, trg = toks[:half], toks[half:]
        return src, trg[:-1], trg[1:]


class WMT14(_WMT):
    """Reference ``text/datasets/wmt14.py`` structure."""


class WMT16(_WMT):
    """Reference ``text/datasets/wmt16.py`` structure."""


__all__ = ["gather_tree", "viterbi_decode", "Imdb", "Imikolov",
           "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]

"""``paddle.text`` parity — text datasets + decode ops.

Analog of ``python/paddle/text/`` (datasets: Imdb/Conll05/...) and the
sequence-decode ops ``viterbi_decode`` (``paddle/phi/kernels/
viterbi_decode_kernel.h``) and ``gather_tree`` (beam-search trace-back).
Datasets ship as synthetic-capable loaders: the reference downloads
corpora; in an air-gapped image we generate deterministic corpora with
identical structure (document in each class docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..io import Dataset


@primitive("gather_tree")
def gather_tree(ids, parents):
    """Beam-search trace-back (reference ``nn/decode gather_tree``):
    ids/parents: [max_time, batch, beam] -> full sequences by walking
    parent pointers from the last step."""
    t, b, k = ids.shape

    def step(carry, inp):
        beams = carry                      # [batch, beam] current beam idx
        id_t, par_t = inp                  # each [batch, beam]
        out = jnp.take_along_axis(id_t, beams, axis=-1)
        nxt = jnp.take_along_axis(par_t, beams, axis=-1)
        return nxt, out

    last = jnp.broadcast_to(jnp.arange(k, dtype=ids.dtype), (b, k))
    _, outs = jax.lax.scan(step, last, (ids[::-1], parents[::-1]))
    return outs[::-1]


@primitive("viterbi_decode")
def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF viterbi decode (reference ``text/viterbi_decode.py``):
    potentials [B, T, N] emissions, transition [N(+2), N(+2)] -> (scores,
    paths [B, T]). With include_bos_eos_tag, the last two transition rows/
    cols are BOS/EOS (reference convention)."""
    b, t, n = potentials.shape
    if include_bos_eos_tag:
        trans = transition_params[:n, :n]
        bos = transition_params[n, :n] if transition_params.shape[0] > n \
            else jnp.zeros((n,))
        eos = transition_params[:n, n + 1] \
            if transition_params.shape[1] > n + 1 else jnp.zeros((n,))
    else:
        trans, bos, eos = transition_params, 0.0, 0.0

    alpha0 = potentials[:, 0] + bos        # [B, N]

    def step(alpha, emit):
        scores = alpha[:, :, None] + trans[None]      # [B, N, N]
        best = jnp.max(scores, axis=1) + emit
        back = jnp.argmax(scores, axis=1)
        return best, back

    alpha, backs = jax.lax.scan(step, alpha0,
                                jnp.swapaxes(potentials[:, 1:], 0, 1))
    alpha = alpha + eos
    last = jnp.argmax(alpha, axis=-1)                 # [B]
    score = jnp.max(alpha, axis=-1)

    def walk(state, back_t):
        prev = jnp.take_along_axis(back_t, state[:, None], -1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(walk, last, backs[::-1])
    paths = jnp.concatenate([path_rev[::-1], last[None]], axis=0)
    return score, jnp.swapaxes(paths, 0, 1).astype(jnp.int64)


class _SyntheticTextDataset(Dataset):
    """Deterministic synthetic corpus with the reference dataset's
    (tokens, label) structure — documented stand-in for the downloadable
    corpora (zero-egress image)."""

    num_classes = 2
    vocab_size = 1000

    def __init__(self, mode="train", n=256, seq_len=64, seed=0):
        rng = np.random.default_rng(
            seed + (0 if mode == "train" else 1))
        self.labels = rng.integers(0, self.num_classes,
                                   n).astype("int64")
        # class-conditional unigram skew so models can actually learn
        base = rng.random((self.num_classes, self.vocab_size))
        base = base / base.sum(-1, keepdims=True)
        self.tokens = np.stack([
            rng.choice(self.vocab_size, seq_len, p=base[c])
            for c in self.labels]).astype("int64")

    def __getitem__(self, i):
        return self.tokens[i], np.asarray([self.labels[i]], "int64")

    def __len__(self):
        return len(self.labels)


class Imdb(_SyntheticTextDataset):
    """Reference ``text/datasets/imdb.py`` structure (binary sentiment)."""


class Imikolov(_SyntheticTextDataset):
    """Reference ``text/datasets/imikolov.py`` (LM ngrams)."""

    def __getitem__(self, i):
        toks = self.tokens[i]
        return toks[:-1], toks[1:]


__all__ = ["gather_tree", "viterbi_decode", "Imdb", "Imikolov"]

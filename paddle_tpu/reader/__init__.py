"""Legacy reader decorators (reference ``python/paddle/reader/decorator.py``:
45-498). A *reader creator* is a zero-arg callable returning an iterable of
samples; these combinators compose creators. Thread-backed where the
reference forks processes (same rationale as paddle_tpu.io: fork is hostile
to a live PJRT client)."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = []


class _Raise:
    """Exception carrier: producer threads forward errors to the consumer
    instead of dying silently (which would truncate or hang the stream)."""

    def __init__(self, exc):
        self.exc = exc


def cache(reader):
    """Cache all samples in memory on first full pass."""
    all_data = tuple(reader())

    def creator():
        for item in all_data:
            yield item
    return creator


def map_readers(func, *readers):
    """Zip readers, map ``func`` over the per-reader samples."""
    def creator():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return creator


def shuffle(reader, buf_size):
    """Buffered shuffle with a ``buf_size`` reservoir."""
    def creator():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return creator


def chain(*readers):
    """Concatenate readers back to back."""
    def creator():
        for r in readers:
            yield from r()
    return creator


class ComposeNotAligned(ValueError):
    """Raised by ``compose(check_alignment=True)`` when readers end at
    different lengths (reference ``decorator.py`` exception of same name)."""


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, b1, b2) from [a], [(b1, b2)].
    ``check_alignment=True`` (default) raises ComposeNotAligned if the
    readers have different lengths; False truncates to the shortest."""
    check_alignment = kwargs.pop('check_alignment', True)
    _end = object()

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def creator():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs, fillvalue=_end):
            if any(o is _end for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(o) for o in outputs), ())
    return creator


def buffered(reader, size):
    """Decouple producer/consumer through a bounded queue (thread).
    Producer exceptions re-raise in the consumer, not die in the thread."""
    end = object()

    def creator():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
                q.put(end)
            except BaseException as e:  # propagate to consumer
                q.put(_Raise(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            if isinstance(e, _Raise):
                raise e.exc
            yield e
    return creator


def firstn(reader, n):
    """Only the first ``n`` samples."""
    def creator():
        yield from itertools.islice(reader(), n)
    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with ``process_num`` worker threads."""
    end = object()

    def creator():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:
                out_q.put(_Raise(e))

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        out_q.put(end)
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:  # a dead worker must not hang the pipe
                out_q.put(_Raise(e))
                out_q.put(end)

        threads = [threading.Thread(target=feed, daemon=True)] + [
            threading.Thread(target=work, daemon=True)
            for _ in range(process_num)]
        for t in threads:
            t.start()

        finished, hold, want = 0, {}, 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _Raise):
                raise item.exc
            i, mapped = item
            if not order:
                yield mapped
            else:
                hold[i] = mapped
                while want in hold:
                    yield hold.pop(want)
                    want += 1
        if order:
            for i in sorted(hold):
                yield hold[i]
    return creator


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave readers concurrently (thread-backed on this runtime)."""
    end = object()

    def creator():
        q = _queue.Queue(queue_size)

        def run(r):
            try:
                for d in r():
                    q.put(d)
                q.put(end)
            except BaseException as e:
                q.put(_Raise(e))

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            e = q.get()
            if e is end:
                finished += 1
            elif isinstance(e, _Raise):
                raise e.exc
            else:
                yield e
    return creator

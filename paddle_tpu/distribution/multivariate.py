"""MultivariateNormal, ContinuousBernoulli, Independent and the
ExponentialFamily base (reference
``python/paddle/distribution/multivariate_normal.py:22``,
``continuous_bernoulli.py:21``, ``independent.py:18``,
``exponential_family.py:20``) — compact jnp implementations.

MultivariateNormal works internally on the Cholesky factor (scale_tril)
whichever parameterization the user gives, like the reference; densities
are closed-form jnp expressions so they jit-fuse and differentiate."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distributions import (Distribution, Tensor, _key, _t, _wrap,
                            register_kl)

__all__ = ["MultivariateNormal", "ContinuousBernoulli", "Independent",
           "ExponentialFamily"]

_LOG_2PI = math.log(2.0 * math.pi)


def _sum_rightmost(x, n):
    return jnp.sum(x, axis=tuple(range(-n, 0))) if n > 0 else x


class MultivariateNormal(Distribution):
    """Reference ``multivariate_normal.py:88``: exactly one of
    ``covariance_matrix`` / ``precision_matrix`` / ``scale_tril``."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = jnp.atleast_1d(_t(loc))
        given = [covariance_matrix is not None, precision_matrix is not None,
                 scale_tril is not None]
        if sum(given) != 1:
            raise ValueError(
                "Exactly one of covariance_matrix or precision_matrix or "
                "scale_tril may be specified.")
        if scale_tril is not None:
            L = _t(scale_tril)
            if L.ndim < 2:
                raise ValueError("scale_tril matrix must be at least "
                                 "two-dimensional")
            self.scale_tril = L
        elif covariance_matrix is not None:
            C = _t(covariance_matrix)
            if C.ndim < 2:
                raise ValueError("covariance_matrix must be at least "
                                 "two-dimensional")
            self.scale_tril = jnp.linalg.cholesky(C)
        else:
            P = _t(precision_matrix)
            if P.ndim < 2:
                raise ValueError("precision_matrix must be at least "
                                 "two-dimensional")
            # reference precision_to_scale_tril (:433): invert the
            # Cholesky factor of the reversed precision
            Lf = jnp.linalg.cholesky(jnp.flip(P, (-2, -1)))
            Linv = jnp.swapaxes(jnp.flip(Lf, (-2, -1)), -2, -1)
            eye = jnp.eye(P.shape[-1], dtype=P.dtype)
            self.scale_tril = jax.scipy.linalg.solve_triangular(
                Linv, jnp.broadcast_to(eye, Linv.shape), lower=True)
        self.covariance_matrix = (
            self.scale_tril @ jnp.swapaxes(self.scale_tril, -2, -1))
        batch = jnp.broadcast_shapes(self.scale_tril.shape[:-2],
                                     self.loc.shape[:-1])
        event = self.loc.shape[-1:]
        self.loc = jnp.broadcast_to(self.loc, batch + event)
        self.scale_tril = jnp.broadcast_to(self.scale_tril,
                                           batch + event + event)
        super().__init__(batch, event)

    @property
    def mean(self):
        return _wrap(self.loc)

    @property
    def variance(self):
        return _wrap(jnp.square(self.scale_tril).sum(-1))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(_key(), shp, self.loc.dtype)
        return _wrap(self.loc + jnp.einsum("...ij,...j->...i",
                                           self.scale_tril, eps))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            diff = v - self.loc
            # Mahalanobis via triangular solve (reference
            # batch_mahalanobis, :452)
            sol = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(self.scale_tril,
                                 jnp.broadcast_shapes(
                                     self.scale_tril.shape,
                                     diff.shape[:-1]
                                     + self.scale_tril.shape[-2:])),
                diff[..., None], lower=True)[..., 0]
            m = jnp.square(sol).sum(-1)
            half_logdet = jnp.log(jnp.diagonal(
                self.scale_tril, axis1=-2, axis2=-1)).sum(-1)
            k = self.loc.shape[-1]
            return -0.5 * (k * _LOG_2PI + m) - half_logdet
        return apply("mvn_log_prob", impl, value)

    def entropy(self):
        half_logdet = jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)).sum(-1)
        k = self.loc.shape[-1]
        return _wrap(0.5 * k * (1.0 + _LOG_2PI) + half_logdet)

    def kl_divergence(self, other):
        return kl_divergence_mvn(self, other)


def kl_divergence_mvn(p: MultivariateNormal, q: MultivariateNormal):
    """Closed-form MVN KL (reference ``multivariate_normal.py:375``)."""
    k = p.loc.shape[-1]
    q_half_logdet = jnp.log(jnp.diagonal(
        q.scale_tril, axis1=-2, axis2=-1)).sum(-1)
    p_half_logdet = jnp.log(jnp.diagonal(
        p.scale_tril, axis1=-2, axis2=-1)).sum(-1)
    # tr(Σq^-1 Σp) = ||Lq^-1 Lp||_F^2
    M = jax.scipy.linalg.solve_triangular(q.scale_tril, p.scale_tril,
                                          lower=True)
    tr = jnp.square(M).sum((-2, -1))
    diff = q.loc - p.loc
    sol = jax.scipy.linalg.solve_triangular(
        q.scale_tril, diff[..., None], lower=True)[..., 0]
    m = jnp.square(sol).sum(-1)
    return _wrap(q_half_logdet - p_half_logdet + 0.5 * (tr + m - k))


class ContinuousBernoulli(Distribution):
    """Reference ``continuous_bernoulli.py:100``: the [0,1]-supported
    exponential-family relaxation of Bernoulli; ``lims`` bounds the
    unstable region around probs=0.5 where the Taylor expansion of the
    normalizer is used."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        p = _t(probs)
        eps = jnp.finfo(p.dtype).eps
        self.probs = jnp.clip(jnp.atleast_1d(p), eps, 1 - eps)
        self.lims = (float(lims[0]), float(lims[1]))
        super().__init__(self.probs.shape, ())

    def _outside(self):
        return (self.probs < self.lims[0]) | (self.probs > self.lims[1])

    def _cut_probs(self):
        # pin the unstable mid-region to the lower lim (reference :154)
        return jnp.where(self._outside(), self.probs,
                         jnp.full_like(self.probs, self.lims[0]))

    def _log_constant(self):
        """log C(p) with the reference's 2nd-order Taylor fallback near
        p=0.5 (reference :177)."""
        cut = self._cut_probs()
        # exact: C(p) = 2*arctanh(1-2p)/(1-2p)
        exact = jnp.log(jnp.abs(jnp.arctanh(1.0 - 2.0 * cut))) \
            - jnp.log(jnp.abs(1.0 - 2.0 * cut)) + math.log(2.0)
        taylor = math.log(2.0) + 4.0 / 3.0 * jnp.square(self.probs - 0.5) \
            + 104.0 / 45.0 * jnp.power(self.probs - 0.5, 4)
        return jnp.where(self._outside(), exact, taylor)

    @property
    def mean(self):
        cut = self._cut_probs()
        exact = cut / (2.0 * cut - 1.0) \
            + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * cut))
        taylor = 0.5 + (self.probs - 0.5) / 3.0 \
            + 16.0 / 45.0 * jnp.power(self.probs - 0.5, 3)
        return _wrap(jnp.where(self._outside(), exact, taylor))

    @property
    def variance(self):
        cut = self._cut_probs()
        exact = cut * (cut - 1.0) / jnp.square(1.0 - 2.0 * cut) \
            + 1.0 / jnp.square(2.0 * jnp.arctanh(1.0 - 2.0 * cut))
        taylor = 1.0 / 12.0 - jnp.square(self.probs - 0.5) / 15.0 \
            - 128.0 / 945.0 * jnp.power(self.probs - 0.5, 4)
        return _wrap(jnp.where(self._outside(), exact, taylor))

    def sample(self, shape=()):
        import jax.lax as lax
        u = jax.random.uniform(
            _key(), tuple(shape) + self.batch_shape, self.probs.dtype)
        return _wrap(lax.stop_gradient(self._icdf(u)))

    def rsample(self, shape=()):
        u = jax.random.uniform(
            _key(), tuple(shape) + self.batch_shape, self.probs.dtype)
        return _wrap(self._icdf(u))

    def _icdf(self, u):
        cut = self._cut_probs()
        ratio = jnp.log1p(-cut) - jnp.log(cut)
        exact = (jnp.log1p(u * jnp.expm1(-ratio)) ) / (-ratio)
        return jnp.where(self._outside(), exact, u)

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            return (v * jnp.log(self.probs)
                    + (1.0 - v) * jnp.log1p(-self.probs)
                    + self._log_constant())
        return apply("continuous_bernoulli_log_prob", impl, value)

    def cdf(self, value):
        v = _t(value)
        cut = self._cut_probs()
        ratio = jnp.log1p(-cut) - jnp.log(cut)
        exact = (jnp.expm1(-ratio * v)) / jnp.expm1(-ratio)
        out = jnp.where(self._outside(), exact, v)
        return _wrap(jnp.clip(out, 0.0, 1.0))

    def entropy(self):
        # E[-log p(X)] with closed-form mean (differential entropy)
        mu = self.mean
        mu_v = mu._read() if isinstance(mu, Tensor) else mu
        return _wrap(-(mu_v * jnp.log(self.probs)
                       + (1.0 - mu_v) * jnp.log1p(-self.probs)
                       + self._log_constant()))

    def kl_divergence(self, other):
        return _kl_continuous_bernoulli(self, other)


def _kl_continuous_bernoulli(p, q):
    """KL(p||q) = E_p[log p - log q] (closed form via E_p[X] = p.mean)."""
    mu = p.mean
    mu_v = mu._read() if isinstance(mu, Tensor) else jnp.asarray(mu)
    t = (mu_v * (jnp.log(p.probs) - jnp.log(q.probs))
         + (1.0 - mu_v) * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs)))
    return _wrap(t + p._log_constant() - q._log_constant())


class Independent(Distribution):
    """Reinterpret ``reinterpreted_batch_rank`` rightmost batch dims of
    ``base`` as event dims (reference ``independent.py:51``)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Distribution):
            raise TypeError("base must be a Distribution")
        n = int(reinterpreted_batch_rank)
        if not 0 < n <= len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank must be in (0, "
                f"{len(base.batch_shape)}], got {n}")
        self.base = base
        self.reinterpreted_batch_rank = n
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        cut = len(base.batch_shape) - n
        super().__init__(shape[:cut], shape[cut:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _t(self.base.log_prob(value))
        return _wrap(_sum_rightmost(lp, self.reinterpreted_batch_rank))

    def prob(self, value):
        return _wrap(jnp.exp(_t(self.log_prob(value))))

    def entropy(self):
        e = _t(self.base.entropy())
        return _wrap(_sum_rightmost(e, self.reinterpreted_batch_rank))


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    from .distributions import kl_divergence
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError(
            "KL between Independents with different batch ranks")
    kl = _t(kl_divergence(p.base, q.base))
    return _wrap(_sum_rightmost(kl, p.reinterpreted_batch_rank))


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    ``exponential_family.py:20``): subclasses provide
    ``_natural_parameters`` and ``_log_normalizer``; ``entropy`` comes
    from the Bregman-divergence identity, with log-normalizer gradients
    taken by jax autodiff (the reference differentiates the static
    graph)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        # H = -E[carrier] + A(eta) - sum_i eta_i * dA/deta_i. A is
        # elementwise over the batch, so grad of A.sum() gives the
        # per-element partials.
        nat = [jnp.asarray(_t(p)) for p in self._natural_parameters]
        grads = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = -jnp.asarray(self._mean_carrier_measure) \
            + self._log_normalizer(*nat)
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return _wrap(ent)


register_kl(MultivariateNormal, MultivariateNormal)(kl_divergence_mvn)
register_kl(ContinuousBernoulli, ContinuousBernoulli)(
    _kl_continuous_bernoulli)

"""Distribution classes. Reference ``python/paddle/distribution/*.py``
(each class docstring cites its file)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def _t(x):
    if isinstance(x, Tensor):
        return x._read()
    return jnp.asarray(x, jnp.float32)


def _wrap(v):
    return Tensor(v) if not isinstance(v, Tensor) else v


def _key():
    return state.default_rng.next_key()


def _shape_of(sample_shape, *params):
    base = jnp.broadcast_shapes(*[jnp.shape(p) for p in params])
    return tuple(sample_shape) + base


class Distribution:
    """Base class (reference ``distribution/distribution.py:44``)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Reference ``distribution/normal.py``."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.loc, self.scale)
        return _wrap(self.loc + self.scale *
                     jax.random.normal(_key(), shp))

    rsample = sample

    def log_prob(self, value):
        from ..core.dispatch import apply
        return apply(
            "normal_log_prob",
            lambda v: (-((v - self.loc) ** 2) / (2 * self.scale ** 2)
                       - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)),
            value)

    def entropy(self):
        v = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(v, self.batch_shape))


class Uniform(Distribution):
    """Reference ``distribution/uniform.py``."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return _wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return _wrap((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        shp = _shape_of(shape, self.low, self.high)
        return _wrap(jax.random.uniform(_key(), shp) *
                     (self.high - self.low) + self.low)

    rsample = sample

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            inside = (v >= self.low) & (v <= self.high)
            return jnp.where(inside, -jnp.log(self.high - self.low),
                             -jnp.inf)
        return apply("uniform_log_prob", impl, value)

    def entropy(self):
        return _wrap(jnp.log(self.high - self.low) +
                     jnp.zeros(self.batch_shape))


class Bernoulli(Distribution):
    """Reference ``distribution/bernoulli.py``."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.probs)
        return _wrap(jax.random.bernoulli(
            _key(), self.probs, shp).astype(jnp.float32))

    def log_prob(self, value):
        from ..core.dispatch import apply
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        return apply(
            "bernoulli_log_prob",
            lambda v: v * jnp.log(p) + (1 - v) * jnp.log1p(-p), value)

    def entropy(self):
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    """Reference ``distribution/categorical.py`` (logits input)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        self._logp = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(jnp.shape(self.logits)[:-1])

    @property
    def probs_(self):
        return jnp.exp(self._logp)

    def sample(self, shape=()):
        shp = tuple(shape) + self.batch_shape
        return _wrap(jax.random.categorical(_key(), self.logits,
                                            shape=shp))

    def probs(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            vi = v.astype(jnp.int32)
            logp = jnp.broadcast_to(self._logp,
                                    vi.shape + self._logp.shape[-1:])
            return jnp.take_along_axis(logp, vi[..., None], -1)[..., 0]
        return apply("categorical_log_prob", impl, value)

    def entropy(self):
        return _wrap(-jnp.sum(jnp.exp(self._logp) * self._logp, -1))


class Beta(Distribution):
    """Reference ``distribution/beta.py``."""

    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.alpha, self.beta)
        return _wrap(jax.random.beta(_key(), self.alpha, self.beta, shp))

    def log_prob(self, value):
        from ..core.dispatch import apply
        a, b = self.alpha, self.beta

        def impl(v):
            lbeta = (jax.scipy.special.gammaln(a) +
                     jax.scipy.special.gammaln(b) -
                     jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return apply("beta_log_prob", impl, value)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a, b = self.alpha, self.beta
        lbeta = gammaln(a) + gammaln(b) - gammaln(a + b)
        return _wrap(lbeta - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                     + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    """Reference ``distribution/dirichlet.py``."""

    def __init__(self, concentration):
        self.concentration = _t(concentration)
        super().__init__(jnp.shape(self.concentration)[:-1],
                         jnp.shape(self.concentration)[-1:])

    @property
    def mean(self):
        c = self.concentration
        return _wrap(c / c.sum(-1, keepdims=True))

    def sample(self, shape=()):
        shp = tuple(shape) + self.batch_shape
        return _wrap(jax.random.dirichlet(_key(), self.concentration,
                                          shp))

    def log_prob(self, value):
        from ..core.dispatch import apply
        c = self.concentration

        def impl(v):
            from jax.scipy.special import gammaln
            norm = gammaln(c).sum(-1) - gammaln(c.sum(-1))
            return ((c - 1) * jnp.log(v)).sum(-1) - norm
        return apply("dirichlet_log_prob", impl, value)


class Gamma(Distribution):
    """Reference ``distribution/gamma.py`` (concentration/rate)."""

    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return _wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return _wrap(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        shp = _shape_of(shape, self.concentration, self.rate)
        return _wrap(jax.random.gamma(_key(), self.concentration, shp) /
                     self.rate)

    def log_prob(self, value):
        from ..core.dispatch import apply
        a, r = self.concentration, self.rate

        def impl(v):
            from jax.scipy.special import gammaln
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v -
                    gammaln(a))
        return apply("gamma_log_prob", impl, value)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a, r = self.concentration, self.rate
        return _wrap(a - jnp.log(r) + gammaln(a) + (1 - a) * digamma(a))


class Binomial(Distribution):
    """Reference ``distribution/binomial.py`` (total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.total_count, self.probs)
        return _wrap(jax.random.binomial(
            _key(), jnp.broadcast_to(self.total_count, shp),
            jnp.broadcast_to(self.probs, shp)))

    def log_prob(self, value):
        from ..core.dispatch import apply
        n, p = self.total_count, self.probs

        def impl(v):
            from jax.scipy.special import gammaln
            comb = (gammaln(n + 1) - gammaln(v + 1) - gammaln(n - v + 1))
            return comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return apply("binomial_log_prob", impl, value)

    def entropy(self):
        # second-order Stirling approximation (exact enumeration for the
        # reference's small-n use is unnecessary here)
        n, p = self.total_count, self.probs
        return _wrap(0.5 * jnp.log(
            2 * jnp.pi * jnp.e * n * p * (1 - p) + 1e-12))


class Exponential(Distribution):
    """Reference ``distribution/exponential.py`` (rate)."""

    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return _wrap(1.0 / self.rate)

    @property
    def variance(self):
        return _wrap(self.rate ** -2)

    def sample(self, shape=()):
        shp = _shape_of(shape, self.rate)
        return _wrap(jax.random.exponential(_key(), shp) / self.rate)

    def log_prob(self, value):
        from ..core.dispatch import apply
        return apply("exponential_log_prob",
                     lambda v: jnp.log(self.rate) - self.rate * v, value)

    def entropy(self):
        return _wrap(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    """Reference ``distribution/laplace.py``."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(2 * self.scale ** 2,
                                      self.batch_shape))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.loc, self.scale)
        return _wrap(self.loc + self.scale *
                     jax.random.laplace(_key(), shp))

    rsample = sample

    def log_prob(self, value):
        from ..core.dispatch import apply
        return apply(
            "laplace_log_prob",
            lambda v: -jnp.abs(v - self.loc) / self.scale -
            jnp.log(2 * self.scale), value)

    def entropy(self):
        return _wrap(1 + jnp.log(2 * self.scale) +
                     jnp.zeros(self.batch_shape))


class LogNormal(Distribution):
    """Reference ``distribution/lognormal.py``."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._normal = Normal(loc, scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        return _wrap(jnp.exp(self._normal.sample(shape)._read()))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            lv = jnp.log(v)
            return (-((lv - self.loc) ** 2) / (2 * self.scale ** 2)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)
                    - lv)
        return apply("lognormal_log_prob", impl, value)


class Gumbel(Distribution):
    """Reference ``distribution/gumbel.py``."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(self.loc + self.scale * np.euler_gamma)

    @property
    def variance(self):
        return _wrap((math.pi ** 2 / 6) * self.scale ** 2 +
                     jnp.zeros(self.batch_shape))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.loc, self.scale)
        return _wrap(self.loc + self.scale *
                     jax.random.gumbel(_key(), shp))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply("gumbel_log_prob", impl, value)


class Cauchy(Distribution):
    """Reference ``distribution/cauchy.py``."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = _shape_of(shape, self.loc, self.scale)
        return _wrap(self.loc + self.scale *
                     jax.random.cauchy(_key(), shp))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            z = (v - self.loc) / self.scale
            return -jnp.log(math.pi * self.scale * (1 + z * z))
        return apply("cauchy_log_prob", impl, value)

    def entropy(self):
        return _wrap(jnp.log(4 * math.pi * self.scale) +
                     jnp.zeros(self.batch_shape))


class Geometric(Distribution):
    """Reference ``distribution/geometric.py`` (k failures before the
    first success, k in {0, 1, ...})."""

    def __init__(self, probs):
        self.probs = _t(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return _wrap((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs ** 2)

    def sample(self, shape=()):
        shp = _shape_of(shape, self.probs)
        u = jax.random.uniform(_key(), shp, minval=1e-7, maxval=1.0)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        from ..core.dispatch import apply
        return apply(
            "geometric_log_prob",
            lambda v: v * jnp.log1p(-self.probs) + jnp.log(self.probs),
            value)


class Poisson(Distribution):
    """Reference ``distribution/poisson.py``."""

    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        shp = _shape_of(shape, self.rate)
        return _wrap(jax.random.poisson(_key(), self.rate,
                                        shp).astype(jnp.float32))

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            from jax.scipy.special import gammaln
            return v * jnp.log(self.rate) - self.rate - gammaln(v + 1)
        return apply("poisson_log_prob", impl, value)


class Multinomial(Distribution):
    """Reference ``distribution/multinomial.py``."""

    def __init__(self, total_count, probs):
        self.total_count = int(unwrap(total_count))
        self.probs = _t(probs)
        super().__init__(jnp.shape(self.probs)[:-1],
                         jnp.shape(self.probs)[-1:])

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    def sample(self, shape=()):
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            _key(), logits, shape=tuple(shape) + (self.total_count,) +
            self.batch_shape)
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(
            axis=len(tuple(shape)))
        return _wrap(counts)

    def log_prob(self, value):
        from ..core.dispatch import apply

        def impl(v):
            from jax.scipy.special import gammaln
            return (gammaln(v.sum(-1) + 1) - gammaln(v + 1).sum(-1) +
                    (v * jnp.log(self.probs)).sum(-1))
        return apply("multinomial_log_prob", impl, value)


# --- KL divergence registry (reference ``distribution/kl.py``) -------------

_KL_REGISTRY: dict = {}


def register_kl(cls_p, cls_q):
    """Reference ``kl.py register_kl`` decorator."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    """Reference ``kl.py kl_divergence`` — registry dispatch with MRO
    fallback."""
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_p, var_q = p.scale ** 2, q.scale ** 2
    out = (jnp.log(q.scale / p.scale) +
           (var_p + (p.loc - q.loc) ** 2) / (2 * var_q) - 0.5)
    return _wrap(out)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    out = jnp.log((q.high - q.low) / (p.high - p.low))
    return _wrap(jnp.where((q.low <= p.low) & (p.high <= q.high), out,
                           jnp.inf))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-7
    a = jnp.clip(p.probs, eps, 1 - eps)
    b = jnp.clip(q.probs, eps, 1 - eps)
    out = a * (jnp.log(a) - jnp.log(b)) + \
        (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b))
    return _wrap(out)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    out = jnp.sum(jnp.exp(p._logp) * (p._logp - q._logp), axis=-1)
    return _wrap(out)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return _wrap(jnp.log(1 / r) + r - 1)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    from jax.scipy.special import digamma, gammaln
    out = ((p.concentration - q.concentration) * digamma(p.concentration)
           - gammaln(p.concentration) + gammaln(q.concentration)
           + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
           + p.concentration * (q.rate / p.rate - 1))
    return _wrap(out)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    from jax.scipy.special import digamma, gammaln

    def lbeta(a, b):
        return gammaln(a) + gammaln(b) - gammaln(a + b)
    sp = p.alpha + p.beta
    out = (lbeta(q.alpha, q.beta) - lbeta(p.alpha, p.beta)
           + (p.alpha - q.alpha) * digamma(p.alpha)
           + (p.beta - q.beta) * digamma(p.beta)
           + (q.alpha - p.alpha + q.beta - p.beta) * digamma(sp))
    return _wrap(out)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    out = (jnp.log(q.scale / p.scale) + d / q.scale +
           p.scale / q.scale * jnp.exp(-d / p.scale) - 1)
    return _wrap(out)

"""TransformedDistribution (reference
``python/paddle/distribution/transformed_distribution.py:24``): push a
base distribution through a chain of Transforms; ``log_prob`` applies the
change-of-variables formula with the inverse log-det Jacobian."""
from __future__ import annotations

import jax.numpy as jnp

from .distributions import Distribution, Tensor, _t, _wrap
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(base, Distribution):
            raise TypeError("base must be a Distribution")
        if isinstance(transforms, Transform):
            transforms = [transforms]
        if not isinstance(transforms, (list, tuple)) or not transforms:
            raise TypeError("transforms must be a non-empty sequence of "
                            "Transforms")
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        shape = chain.forward_shape(
            tuple(base.batch_shape) + tuple(base.event_shape))
        super().__init__(batch_shape=shape, event_shape=())
        self._chain = chain

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        y = _t(value)
        lp = 0.0
        for t in reversed(self.transforms):
            x = t._inverse(y)
            lp = lp - t._forward_log_det_jacobian(x)
            y = x
        base_lp = _t(self.base.log_prob(_wrap(y)))
        return _wrap(base_lp + lp)

    def prob(self, value):
        return _wrap(jnp.exp(_t(self.log_prob(value))))

"""TransformedDistribution (reference
``python/paddle/distribution/transformed_distribution.py:24``): push a
base distribution through a chain of Transforms; ``log_prob`` applies the
change-of-variables formula with the inverse log-det Jacobian, tracking
per-transform event ranks and summing the rightmost dims at each hop the
way the reference's ``_sum_rightmost`` does (``transform.py:566``)."""
from __future__ import annotations

import jax.numpy as jnp

from .distributions import Distribution, Tensor, _t, _wrap
from .transform import ChainTransform, Transform, _sum_rightmost


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(base, Distribution):
            raise TypeError("base must be a Distribution")
        if isinstance(transforms, Transform):
            transforms = [transforms]
        if not isinstance(transforms, (list, tuple)) or not transforms:
            raise TypeError("transforms must be a non-empty sequence of "
                            "Transforms")
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        base_event_ndim = len(base.event_shape)
        domain_ndim = chain._domain_event_ndim
        if len(base_shape) < domain_ndim:
            raise ValueError(
                f"base distribution's shape {base_shape} has fewer dims "
                f"than the transform's domain event rank {domain_ndim}")
        fwd_shape = chain.forward_shape(base_shape)
        # event rank of the result: what the chain emits, plus any base
        # event dims the chain never consumed
        event_ndim = (chain._codomain_event_ndim
                      + max(base_event_ndim - domain_ndim, 0))
        cut = len(fwd_shape) - event_ndim
        super().__init__(batch_shape=fwd_shape[:cut],
                         event_shape=fwd_shape[cut:])
        self._chain = chain

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        y = _t(value)
        lp = 0.0
        event_ndim = len(self.event_shape)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            event_ndim += t._domain_event_ndim - t._codomain_event_ndim
            ld = t._forward_log_det_jacobian(x)
            lp = lp - _sum_rightmost(
                ld, event_ndim - t._domain_event_ndim)
            y = x
        base_lp = _t(self.base.log_prob(_wrap(y)))
        base_lp = _sum_rightmost(
            base_lp, event_ndim - len(self.base.event_shape))
        return _wrap(base_lp + lp)

    def prob(self, value):
        return _wrap(jnp.exp(_t(self.log_prob(value))))

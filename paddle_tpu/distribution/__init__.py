"""``paddle.distribution`` parity.

Analog of ``python/paddle/distribution/`` (Distribution base
``distribution.py:44``, Normal/Uniform/Categorical/Bernoulli/Beta/
Dirichlet/Gamma/..., ``kl.py`` kl_divergence + register_kl). TPU-native:
densities are jnp expressions behind the dispatch funnel (so log_prob is
differentiable and jit-fusible); sampling draws from the framework PRNG
(``paddle.seed``) via ``jax.random``.
"""
from .distributions import (  # noqa: F401
    Distribution, Normal, Uniform, Bernoulli, Categorical, Beta,
    Dirichlet, Gamma, Binomial, Exponential, Laplace, LogNormal, Gumbel, Cauchy,
    Geometric, Poisson, Multinomial, kl_divergence, register_kl,
)
from .transform import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform,
    ExpTransform, IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform,
)
from .multivariate import (  # noqa: F401
    ContinuousBernoulli, ExponentialFamily, Independent,
    MultivariateNormal,
)
from .transformed_distribution import TransformedDistribution  # noqa: F401

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
    "Beta", "Dirichlet", "Gamma", "Binomial", "Exponential", "Laplace", "LogNormal",
    "Gumbel", "Cauchy", "Geometric", "Poisson", "Multinomial",
    "kl_divergence", "register_kl",
    "MultivariateNormal", "ContinuousBernoulli", "Independent",
    "ExponentialFamily",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]

"""Probability transforms (reference
``python/paddle/distribution/transform.py``: Transform ``:59``,
AbsTransform ``:350``, AffineTransform ``:422``, ChainTransform ``:504``,
ExpTransform ``:629``, IndependentTransform ``:678``, PowerTransform
``:773``, ReshapeTransform ``:837``, SigmoidTransform ``:960``,
SoftmaxTransform ``:1003``, StackTransform ``:1059``,
StickBreakingTransform ``:1179``, TanhTransform ``:1245``).

Pure-jnp bijector algebra: forward / inverse / log-det-Jacobian pairs with
shape propagation, composing via ChainTransform and lifting over batch
dims via IndependentTransform. Consumed by TransformedDistribution."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distributions import Tensor, _t, _wrap

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


def _sum_rightmost(x, n):
    """Sum the ``n`` rightmost dims (reference ``transform.py``'s
    ``_sum_rightmost``); n==0 is the identity."""
    if n <= 0:
        return x
    return jnp.sum(x, axis=tuple(range(-n, 0)))


class Transform:
    """Bijector base (reference ``transform.py:59``)."""

    _is_injective = True
    # how many rightmost dims one application consumes (event ndim)
    _domain_event_ndim = 0
    _codomain_event_ndim = 0

    def forward(self, x):
        return _wrap(self._forward(_t(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_t(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_t(x)))

    def inverse_log_det_jacobian(self, y):
        y = _t(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # ---- jnp-level implementations (subclasses override) ----
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def __call__(self, x):
        from .transformed_distribution import TransformedDistribution
        from .distributions import Distribution
        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        return self.forward(x)


class AbsTransform(Transform):
    """y = |x| (reference ``:350``). Not injective: ``inverse`` returns
    the positive preimage like the reference."""

    _is_injective = False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def inverse_log_det_jacobian(self, y):
        return _wrap(jnp.zeros_like(_t(y)))


class AffineTransform(Transform):
    """y = loc + scale * x (reference ``:422``)."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    """y = exp(x) (reference ``:629``)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on the positive reals (reference ``:773``)."""

    def __init__(self, power):
        self.power = _t(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference ``:960``)."""

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x) (reference ``:1245``)."""

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x)), numerically
        # stable for large |x| (same identity as the reference)
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    """Composition t_n(...t_1(x)) (reference ``:504``)."""

    def __init__(self, transforms):
        if not isinstance(transforms, (list, tuple)) or not transforms:
            raise TypeError("ChainTransform expects a non-empty sequence "
                            "of Transforms")
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.transforms = list(transforms)
        self._is_injective = all(t._is_injective for t in transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    @property
    def _domain_event_ndim(self):
        # walk backward from the last codomain, widening for any part
        # that consumes more event dims than the running value (reference
        # sums rightmost dims per part via _sum_rightmost; the chain's
        # domain rank is the widest requirement propagated to the input)
        event = self.transforms[-1]._codomain_event_ndim
        for t in reversed(self.transforms):
            event += t._domain_event_ndim - t._codomain_event_ndim
            event = max(event, t._domain_event_ndim)
        return event

    @property
    def _codomain_event_ndim(self):
        event = self.transforms[0]._domain_event_ndim
        for t in self.transforms:
            event += t._codomain_event_ndim - t._domain_event_ndim
            event = max(event, t._codomain_event_ndim)
        return event

    def _forward_log_det_jacobian(self, x):
        # per-part log-dets live at different event ranks; reduce each to
        # the chain's domain rank before accumulating (reference
        # transform.py:566 _sum_rightmost)
        total = 0.0
        event = self._domain_event_ndim
        for t in self.transforms:
            ld = t._forward_log_det_jacobian(x)
            total = total + _sum_rightmost(ld, event - t._domain_event_ndim)
            event += t._codomain_event_ndim - t._domain_event_ndim
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class IndependentTransform(Transform):
    """Reinterpret ``reinterpreted_batch_ndims`` rightmost batch dims as
    event dims: the log-det sums over them (reference ``:678``)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        if not isinstance(base, Transform):
            raise TypeError("base must be a Transform")
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)

    @property
    def _domain_event_ndim(self):
        return self.base._domain_event_ndim + self.reinterpreted_batch_ndims

    @property
    def _codomain_event_ndim(self):
        return (self.base._codomain_event_ndim
                + self.reinterpreted_batch_ndims)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        axes = tuple(range(-self.reinterpreted_batch_ndims, 0))
        return jnp.sum(ld, axis=axes) if axes else ld

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ReshapeTransform(Transform):
    """Reshape the event part (reference ``:837``)."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_ndim = len(self.in_event_shape)
        self._codomain_event_ndim = len(self.out_event_shape)
        import numpy as _np
        if int(_np.prod(self.in_event_shape)) != int(
                _np.prod(self.out_event_shape)):
            raise ValueError("in/out event shapes must have equal size")

    def _batch(self, shape, event):
        n = len(shape) - len(event)
        if n < 0 or tuple(shape[n:]) != tuple(event):
            raise ValueError(f"shape {shape} does not end in {event}")
        return tuple(shape[:n])

    def _forward(self, x):
        b = self._batch(x.shape, self.in_event_shape)
        return jnp.reshape(x, b + self.out_event_shape)

    def _inverse(self, y):
        b = self._batch(y.shape, self.out_event_shape)
        return jnp.reshape(y, b + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        b = self._batch(x.shape, self.in_event_shape)
        return jnp.zeros(b, jnp.float32)

    def forward_shape(self, shape):
        return self._batch(shape, self.in_event_shape) \
            + self.out_event_shape

    def inverse_shape(self, shape):
        return self._batch(shape, self.out_event_shape) \
            + self.in_event_shape


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last dim (reference ``:1003``; like the
    reference, not a bijection — no log-det)."""

    _is_injective = False
    _domain_event_ndim = 1
    _codomain_event_ndim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        x = jnp.log(y)
        return x - x.mean(axis=-1, keepdims=True)


class StackTransform(Transform):
    """Apply transforms[i] to slice i along ``axis`` (reference
    ``:1059``)."""

    def __init__(self, transforms, axis=0):
        if not isinstance(transforms, (list, tuple)) or not transforms:
            raise TypeError("StackTransform expects a non-empty sequence")
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.transforms = list(transforms)
        self.axis = int(axis)

    @property
    def _domain_event_ndim(self):
        # the stack axis selects which transform applies — it is a batch
        # dim, so the event rank is the widest component's
        return max(t._domain_event_ndim for t in self.transforms)

    @property
    def _codomain_event_ndim(self):
        return max(t._codomain_event_ndim for t in self.transforms)

    def _map(self, fn_name, v):
        parts = jnp.split(v, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(jnp.squeeze(p, self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """Unconstrained R^K -> (K+1)-simplex via stick breaking (reference
    ``:1179``)."""

    _domain_event_ndim = 1
    _codomain_event_ndim = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate(
            [z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z], axis=-1)
        return zpad * jnp.cumprod(one_minus, axis=-1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        k = y_crop.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        rem = 1.0 - jnp.cumsum(y_crop, axis=-1) + y_crop
        z = y_crop / rem
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z[..., :-1]],
            axis=-1)
        rem = jnp.cumprod(one_minus, axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rem), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)

"""``paddle.regularizer`` namespace (reference
``python/paddle/regularizer.py``) — re-exports the decay classes the
optimizers consume."""
from .optimizer.optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]

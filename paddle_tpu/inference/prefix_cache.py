"""Cross-request KV prefix cache: a radix index over the page pool
with per-page refcounts, copy-on-write, and LRU eviction (ISSUE 6).

At serving scale most traffic shares long system prompts and few-shot
prefixes, yet an uncached engine re-prefills every admission from token
zero — and preempt-and-requeue even recomputes prefill work the engine
already did once.  Because the ragged paged-attention kernel treats
block tables and lengths as DATA (PAPERS.md #1), mapping a shared
prefix onto already-written pages is purely a block-table indirection:
no kernel change, no recompile, and the attended values are
bitwise-identical to the ones this request's own prefill would have
written (KV at position ``p`` is a deterministic function of tokens
``[0..p]`` under causal attention and eval-mode determinism).

Design — three layers over one page pool:

* **Radix/trie index, page-granular.**  Each trie edge is the exact
  token content of ONE full page (``page_size`` tokens, keyed by their
  bytes), so a path from the root spells a prefix and each node maps
  it to the immutable KV page holding those positions.  Only FULLY
  written pages are ever published; partial tail pages stay private.
  Matching walks the request's tokens page-by-page and stops at the
  first miss — prefill then starts at the first uncached token.
* **Per-page refcounts layered onto the free list.**  Every page is in
  exactly one of three states: FREE (on the engine's free list),
  IN USE (``ref > 0``: referenced by one or more resident slots — a
  private page has ref 1, a shared prefix page ref = #residents using
  it), or CACHED (``ref == 0`` but owned by a trie node: reclaimable).
  ``acquire``/``retain``/``release`` move pages between states;
  conservation (``in_use + free + cached == total - 1``, page 0 is the
  engine's reserved null page) is checkable at every step via
  :meth:`PrefixCache.check` and drilled by the randomized property
  test (``tests/test_prefix_cache.py``).
* **LRU eviction, leaf-first.**  Under pool pressure ``acquire``
  reclaims the least-recently-used ref-0 cached page before the engine
  resorts to preempting a resident.  Only trie LEAVES are evicted (an
  interior page's descendants would become unreachable garbage);
  because a matched path is retained root-to-tip, a ref-0 node's whole
  subtree is ref-0, so every cached page is eventually reclaimable by
  repeated leaf eviction and ``available()`` may count all of them.

Copy-on-write sits at the divergence page: when a request's ENTIRE
(page-aligned) token sequence is cached there is nothing left to
prefill, yet the engine still needs the last position's logits — so the
last matched page is not shared but COPIED (device-side, one dispatch,
see ``ContinuousBatchingEngine._cow_page``) and the one recomputed
token's KV write lands on the private copy, never on the shared page.
Every other case starts prefill at a page boundary past the matched
prefix, so shared pages are never write targets (the engine's write
path routes by ``block_table[slot, pos // page_size]``).

``enabled=False`` (the ``serving_prefix_cache`` flag's ``off`` value)
keeps the refcount bookkeeping — one code path, same invariants — but
never indexes or matches, which restores the uncached engine bitwise.

Quantized KV (ISSUE 7) note — SCALE TRAVEL: under ``kv_quant`` the
engine's page pools are int8 with per-page scale side-pools indexed by
the SAME page ids this cache hands around.  The cache itself never
touches tensor data (it moves page IDS between free/in-use/cached), so
a published page implicitly publishes its scale vector, a matched page
brings its scales along through the block-table indirection, and the
engine's COW copy program duplicates data and scale pools in the same
dispatch.  Quantized bytes are also write-path-independent (per-token
absmax, ``quantization.kv_quantize``), so a cache hit reconstructs
exactly the bytes the request's own prefill would have written — the
cache-on/off parity suite re-runs with ``serving_kv_quant=on``
unchanged.
"""
from __future__ import annotations

from collections import deque

import numpy as np

from ..core.errors import CacheIntegrityError
from ..resilience import faults
from ..resilience.serving import SITE_CACHE_EVICT

__all__ = ["PrefixCache"]


class _Node:
    """One radix-tree node: a full page of tokens (edge label = their
    bytes, held by the parent's ``children`` dict) mapped to the KV
    page that holds their positions."""

    __slots__ = ("page", "parent", "key", "children")

    def __init__(self, page, parent, key):
        self.page = page          # page id (cache-owned while linked)
        self.parent = parent      # _Node | None (root)
        self.key = key            # bytes of this page's tokens
        self.children: dict[bytes, _Node] = {}


class PrefixCache:
    """Radix index + refcounted page accounting over the engine's free
    list (the ``deque`` is shared with the engine, not copied — the
    existing free-list discipline stays observable).

    The engine calls: :meth:`match` at admission (then :meth:`retain`
    to pin the matched pages), :meth:`acquire` wherever it used to pop
    the free list, :meth:`publish` at retirement/preemption, and
    :meth:`release` wherever it used to extend the free list back.
    """

    def __init__(self, page_size: int, free_pages: deque, *,
                 enabled: bool = True, total_pages: int | None = None):
        self.page_size = int(page_size)
        self.free = free_pages
        self.enabled = bool(enabled)
        # total pool size for the conservation check; the free list at
        # construction holds every usable page, so default from it
        self.total_pages = (1 + len(free_pages) if total_pages is None
                            else int(total_pages))
        self.root = _Node(0, None, b"")
        self._ref: dict[int, int] = {}        # page -> resident refs
        self._page_node: dict[int, _Node] = {}  # cache-owned pages
        self._lru: dict[int, int] = {}        # ref-0 cached: page->tick
        self._tick = 0
        # the one counter the engine folds into its stats snapshot
        # (hit accounting lives in the engine: its numbers are
        # COW-adjusted tokens-not-recomputed, not raw match length)
        self.evictions = 0       # cached pages reclaimed under pressure

    # ------------------------------------------------------ gauges ----
    @property
    def cached_pages(self) -> int:
        """Ref-0 pages held only by the index (reclaimable)."""
        return len(self._lru)

    def cached_page_ids(self):
        return sorted(self._lru)

    def available(self) -> int:
        """Pages an allocation could obtain without preempting anyone:
        the free list plus every evictable cached page."""
        return len(self.free) + len(self._lru)

    def _touch(self):
        self._tick += 1
        return self._tick

    # --------------------------------------------------- allocation ---
    def acquire(self, key: str = "") -> int | None:
        """One page for a resident slot (ref starts at 1): from the
        free list, else by evicting the LRU cached page.  ``None`` when
        both are dry (the engine preempts then).  The deterministic
        ``engine_cache_evict`` drill (``key`` = requesting rid) forces
        the eviction path while free pages remain."""
        if faults.check(SITE_CACHE_EVICT, key=str(key)) and self._lru:
            self._evict_lru()
        if not self.free:
            if not self._lru or self._evict_lru() < 0:
                return None
            if not self.free:       # defensive: eviction must feed it
                return None
        pg = self.free.popleft()
        if self._ref.get(pg, 0) != 0:
            raise CacheIntegrityError(
                f"page {pg} on the free list with refcount "
                f"{self._ref[pg]} [{CacheIntegrityError.error_code}]")
        self._ref[pg] = 1
        return pg

    def retain(self, pages) -> None:
        """Pin matched pages for a resident slot (ref++); a ref-0
        cached page leaves the LRU pool (no longer evictable)."""
        for pg in pages:
            self._ref[pg] = self._ref.get(pg, 0) + 1
            self._lru.pop(pg, None)

    def release(self, pages) -> None:
        """Drop one resident reference per page: a zero-ref page
        returns to the LRU pool when the index owns it, else to the
        free list.  The ONLY way pages leave a slot."""
        for pg in pages:
            ref = self._ref.get(pg, 0)
            if ref <= 0:
                raise CacheIntegrityError(
                    f"double-free: page {pg} released with refcount "
                    f"{ref} [{CacheIntegrityError.error_code}]")
            self._ref[pg] = ref - 1
            if ref == 1:
                if pg in self._page_node:
                    self._lru[pg] = self._touch()
                else:
                    self.free.append(pg)

    # ------------------------------------------------------- index ----
    def _chunks(self, ids, n_pages):
        ids = np.asarray(ids, np.int32)
        ps = self.page_size
        for i in range(n_pages):
            yield ids[i * ps:(i + 1) * ps].tobytes()

    def match(self, ids) -> list[int]:
        """Walk the trie over ``ids`` page-by-page; returns the pages
        of the longest cached prefix (NOT yet retained — the engine
        pins them with :meth:`retain` once it commits the admission).
        Matching refreshes the path's LRU recency."""
        if not self.enabled:
            return []
        n = int(np.asarray(ids).size) // self.page_size
        node, pages = self.root, []
        for key in self._chunks(ids, n):
            child = node.children.get(key)
            if child is None:
                break
            pages.append(child.page)
            if child.page in self._lru:
                self._lru[child.page] = self._touch()
            node = child
        return pages

    def publish(self, ids, pages, n_tokens) -> int:
        """Index a retiring/preempted slot's FULL pages: ``ids`` are
        the tokens whose KV is resident, ``pages`` the slot's page list
        (positional), ``n_tokens`` how many positions are actually
        written.  Pages adopted by a new trie node become cache-owned
        (they go to the LRU pool when the slot releases them); a path
        segment already indexed — by this request's own earlier
        preemption or by a concurrent twin — keeps the incumbent page
        and the slot's duplicate stays private (freed on release).
        Returns the number of newly indexed pages."""
        if not self.enabled:
            return 0
        n = min(int(n_tokens) // self.page_size, len(pages))
        node, new = self.root, 0
        for i, key in enumerate(self._chunks(ids, n)):
            child = node.children.get(key)
            if child is None:
                pg = int(pages[i])
                if pg in self._page_node:   # already owned elsewhere
                    break                   # (same bytes can't own 2x)
                child = _Node(pg, node, key)
                node.children[key] = child
                self._page_node[pg] = child
                new += 1
            node = child
        return new

    # ---------------------------------------------------- eviction ----
    def _evict_lru(self) -> int:
        """Reclaim the least-recently-used EVICTABLE cached page (a
        trie leaf — interior pages wait until their subtree drains) and
        put it on the free list."""
        page = min(
            (pg for pg in self._lru if not self._page_node[pg].children),
            key=self._lru.__getitem__, default=None)
        if page is None:       # only interior ref-0 pages: cannot
            return -1          # happen (subtrees of ref-0 are ref-0)
        node = self._page_node.pop(page)
        node.parent.children.pop(node.key, None)
        self._lru.pop(page)
        self._ref.pop(page, None)
        self.free.append(page)
        self.evictions += 1
        # event-ring breadcrumb (ISSUE 8): cache churn is the first
        # thing a TTFT-regression postmortem looks for
        from ..observability import events as _events
        _events.emit("serving.cache_evict", page=int(page),
                     evictions=int(self.evictions))
        return page

    # ------------------------------------------------- invariants -----
    def check(self) -> None:
        """Page-conservation audit; raises :class:`CacheIntegrityError`
        (PDT-E019) on any violation.  Cheap enough for tests to call
        after every mutation (the randomized property test does)."""
        free = list(self.free)
        free_set = set(free)
        code = CacheIntegrityError.error_code
        if len(free) != len(free_set):
            raise CacheIntegrityError(
                f"free list holds duplicates [{code}]")
        if 0 in free_set or 0 in self._page_node or 0 in self._lru:
            raise CacheIntegrityError(
                f"null page 0 entered the allocator [{code}]")
        in_use = {p for p, r in self._ref.items() if r > 0}
        if in_use & free_set:
            raise CacheIntegrityError(
                f"pages both free and referenced: "
                f"{sorted(in_use & free_set)} [{code}]")
        cached = set(self._lru)
        if cached & free_set or cached & in_use:
            raise CacheIntegrityError(
                f"cached pages overlap free/in-use [{code}]")
        for pg in cached:
            if pg not in self._page_node:
                raise CacheIntegrityError(
                    f"LRU page {pg} not owned by the index [{code}]")
        total = len(in_use) + len(free_set) + len(cached)
        if total != self.total_pages - 1:
            raise CacheIntegrityError(
                f"page conservation broken: {len(in_use)} in use + "
                f"{len(free_set)} free + {len(cached)} cached != "
                f"{self.total_pages - 1} usable pages [{code}]")
        # every owned page is either pinned by a resident or in the LRU
        for pg, node in self._page_node.items():
            if self._ref.get(pg, 0) == 0 and pg not in self._lru:
                raise CacheIntegrityError(
                    f"owned ref-0 page {pg} missing from the LRU pool "
                    f"[{code}]")
            if node.parent.children.get(node.key) is not node:
                raise CacheIntegrityError(
                    f"trie link broken for page {pg} [{code}]")

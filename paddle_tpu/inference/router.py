"""Fleet-scale serving: a fault-tolerant multi-replica router.

One engine — even TP-sharded (ISSUE 13) and disaggregated — is not
"millions of users".  :class:`FleetRouter` is the front-end that
spreads traffic over N replica workers (:class:`~paddle_tpu.inference.
engine.ContinuousBatchingEngine` or :class:`~paddle_tpu.inference.
distserve.DisaggServer`, in-process or over ``distributed/rpc`` via
:class:`RpcReplica`), composing the pieces the stack already has —
queryable radix prefix caches (ISSUE 6), the SLO burn-rate engine
(ISSUE 14), the elastic heartbeat/generation detector (ISSUE 15) and
the ``engine_decode_worker_lost`` requeue path (ISSUE 13) — into a
survivable fleet:

* PREFIX-CACHE-AWARE PLACEMENT — each prompt is routed to the replica
  whose radix trie reports the longest page-aligned prefix hit
  (``cached_prefix_tokens``: the trie makes hit-length queryable
  without hashing heuristics), so shared-prefix traffic concentrates
  where its KV pages already live; prompts no replica has cached spill
  to the least-loaded replica (live in-flight gauge, deterministic
  index tie-break).  ``affinity=False`` restores deterministic
  round-robin — placement never changes outputs (greedy decode is
  batch-invariant), only cache-hit tokens move.
* PER-TENANT QoS — :class:`TenantSpec` declares priority class,
  fair-share weight, an optional per-tenant queue bound and per-tenant
  ``SLOSpec`` objectives.  Admission is strict-priority across
  classes and weighted stride scheduling (virtual-time fair queueing)
  within a class, so a storm tenant cannot starve a light tenant
  below its weight share.  Queue bounds surface the engine's own
  coded policies: ``reject`` raises ``QueueFullError`` (PDT-E017),
  ``block`` steps the fleet until room frees; requests that can never
  fit ANY replica's page pool fail eagerly with ``PageBudgetError``
  (PDT-E016).
* REPLICA FAILURE HANDLING (the robustness core) — every replica
  carries a heartbeat (refreshed by each successful step) and a
  generation number; each step is watchdog-armed so a HUNG replica
  surfaces ``EngineStallError`` (PDT-E020) with a flight record
  instead of wedging the router.  A dead replica — heartbeat timeout,
  stalled step, exhausted placement retries, or the
  ``router_replica_lost`` drill — bumps the fleet generation, writes
  exactly one coded flight record (``ReplicaLostError`` PDT-E024) and
  requeues its queued AND in-flight requests to the survivors at the
  front of their tenant queues: a from-scratch re-prefill that
  restores from the survivors' prefix caches where pages match.
  Greedy decode is deterministic and batch-invariant, so the requeued
  outputs are bitwise-identical to an unfaulted run — a lost replica
  costs latency, never a request.
* ELASTIC SCALE-OUT/IN — ``fleet_slo=`` arms the ISSUE-14 SLO engine
  over the router's own registry (``queue_p95_ms`` latency and
  ``goodput`` ratio shorthands are fed by the router); a sustained
  multi-window burn-rate breach admits a standby replica (warm model
  — compiled serving programs cache on the shared model, so the
  standby compiles nothing; cold cache), and a recovered SLO held for
  ``scalein_hold_s`` drains it back to standby.  If every live
  replica dies, a standby is admitted immediately (failover needs no
  SLO verdict).
* LIVE MIGRATION & GRACEFUL DRAIN (ISSUE 20) — with ``migration`` on
  (``serving_migration``), :meth:`FleetRouter.drain` and SLO scale-in
  MIGRATE a replica's resident requests to the survivors instead of
  waiting them out: the engine's ``snapshot_request`` (tokens so far,
  decode position, remaining deadline, warm KV pages + CRC) ships
  over ``KVPageTransport.ship_snapshot`` (bounded ``resilience.retry``)
  and ``restore_request`` rebuilds the slot on the destination through
  the PR13 import scatter.  Greedy decode is deterministic and
  batch-invariant, so the migrated stream is token-for-token identical
  to the unmigrated one, and a mid-prefill move keeps the finished
  chunks — planned preemption loses zero prefill work.  A transfer
  that fails past the retry budget falls back to the PR17 cold
  requeue (front of the tenant queue, demand counted once) with
  exactly one coded flight record (``MigrationError`` PDT-E025); a
  torn (CRC-invalid) snapshot is rejected at restore and the source
  keeps serving the request.  LAME-DUCK mode answers planned
  preemption (``resilience.preempt``'s SIGTERM flag, polled once per
  ``step()``) and degraded heartbeats (``lameduck_ms``): the replica
  stops taking placements, its residents migrate warm, and the
  emptied replica parks in standby before the eviction lands.

Observability: the router owns a ``serving_router`` registry —
always-on counters (the ``stats`` contract), ``serving.queue_ms`` /
``serving.finished`` fed per completion (the fleet SLO's inputs),
per-replica labeled load/state/generation gauges, and
``router.place`` / ``router.step`` / ``router.scaleout`` tracing
spans.  With ``PDTPU_METRICS=off`` everything degrades to the engine
contract: outputs bitwise-identical, ``stats`` still counts, SLO
judgment (and therefore SLO-driven scaling) is off.
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np

from ..core.errors import (EngineStallError, MigrationError,
                           PageBudgetError, QueueFullError,
                           ReplicaLostError)
from ..core.tensor import Tensor
from ..observability import Registry as _ObsRegistry
from ..observability import events as _events
from ..observability import flight as _flight
from ..observability import metrics as _obs_metrics
from ..observability import slo as _slo_mod
from ..observability import tracing as _tracing
from ..observability import watchdog as _watchdog
from ..observability.metrics import LATENCY_BUCKETS_MS
from ..observability.serving import RegistryCounters
from ..resilience import faults
from ..resilience import preempt as _preempt
from ..resilience.retry import retry_call
from ..resilience.serving import (SITE_ROUTER_DISPATCH_TRANSIENT,
                                  SITE_ROUTER_REPLICA_LOST,
                                  SITE_ROUTER_SCALEOUT_STALL,
                                  simulated_stall)
from .distserve import KVPageTransport
from .engine import CompletedRequest, ContinuousBatchingEngine

__all__ = ["FleetRouter", "TenantSpec", "RpcReplica",
           "register_replica_worker", "rpc_replica_call"]


# --------------------------------------------------------------- rpc --
# Same shape as distserve's decode-worker registry: the worker process
# registers its engine under a name after rpc.init_rpc, and the router
# holds an RpcReplica proxy that forwards the replica surface.

_REPLICA_WORKERS: dict = {}


def register_replica_worker(name: str, engine) -> None:
    """Expose ``engine`` to rpc-backed fleet routing under ``name``
    (call on the replica worker process after ``rpc.init_rpc``)."""
    _REPLICA_WORKERS[str(name)] = engine


def rpc_replica_call(name: str, method: str, args: tuple, kwargs: dict):
    """Server-side half of an rpc replica: dispatch ``method`` on the
    registered engine.  ``fleet_limits`` is synthesized here so the
    router can size eager admission without a remote attribute
    protocol."""
    eng = _REPLICA_WORKERS.get(str(name))
    if eng is None:
        raise KeyError(f"no replica worker registered as {name!r}")
    if method == "fleet_limits":
        return _probe_limits(eng)
    out = getattr(eng, method)
    if callable(out):
        return out(*args, **kwargs)
    return out   # property surface (stats, has_work)


class RpcReplica:
    """Client-side proxy: the replica surface over ``distributed/rpc``.

    ``to`` is the rpc peer; ``worker`` the name the engine was
    registered under (defaults to ``to``).  Results (completions,
    stats dicts, hit lengths) come back pickled by the rpc layer; a
    dead peer raises ``ConnectionError``, which the router treats as a
    lost replica.
    """

    def __init__(self, to: str, worker: str = None, timeout: float = None):
        self.to = str(to)
        self.worker = str(worker or to)
        self.timeout = timeout

    def _call(self, method, *args, **kwargs):
        from ..distributed.rpc import rpc_sync
        kw = {} if self.timeout is None else {"timeout": self.timeout}
        return rpc_sync(self.to, rpc_replica_call,
                        args=(self.worker, method, args, kwargs), **kw)

    def fleet_limits(self) -> dict:
        return self._call("fleet_limits")

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    request_id=None, deadline_ms=None, requeue=False):
        return self._call(
            "add_request", np.asarray(prompt, np.int32),
            int(max_new_tokens), eos_token_id=eos_token_id,
            request_id=request_id, deadline_ms=deadline_ms,
            requeue=requeue)

    def step(self):
        return self._call("step")

    def cancel(self, rid):
        return self._call("cancel", rid)

    def cached_prefix_tokens(self, ids) -> int:
        return int(self._call("cached_prefix_tokens",
                              np.asarray(ids, np.int32)))

    def snapshot_request(self, rid):
        return self._call("snapshot_request", rid)

    def restore_request(self, payload, max_new_tokens=None,
                        request_id=None, deadline_ms=None):
        return self._call("restore_request", payload,
                          max_new_tokens=max_new_tokens,
                          request_id=request_id, deadline_ms=deadline_ms)

    def discard_request(self, rid) -> bool:
        return bool(self._call("discard_request", rid))

    def pending_requests(self):
        return self._call("pending_requests")

    def metrics(self):
        return self._call("metrics")

    def slo_status(self):
        return self._call("slo_status")

    @property
    def stats(self):
        return self._call("stats")

    @property
    def has_work(self):
        return bool(self._call("has_work"))


def _probe_limits(engine) -> dict:
    """The capacity facts eager admission and placement need, for any
    replica kind.  DisaggServer sizes against its DECODE group — the
    group that must hold the full sequence (its own add_request
    validates the same way)."""
    if isinstance(engine, RpcReplica) or hasattr(engine, "fleet_limits"):
        return dict(engine.fleet_limits())
    if hasattr(engine, "decode_group"):
        dec = engine.decode_group[0]
        return {"max_seq_len": int(dec.max_seq_len),
                "page_size": int(dec.page_size),
                "total_pages": int(dec.total_pages),
                "max_slots": sum(int(e.max_slots)
                                 for e in engine.decode_group)}
    return {"max_seq_len": int(engine.max_seq_len),
            "page_size": int(engine.page_size),
            "total_pages": int(engine.total_pages),
            "max_slots": int(engine.max_slots)}


# ------------------------------------------------------------ tenants --
class TenantSpec:
    """One tenant's QoS contract.

    ``priority`` is a strict class (lower serves first — an admission
    from class 0 always beats class 1); ``weight`` is the fair share
    WITHIN the class (stride scheduling: a weight-3 tenant gets ~3x
    the admissions of a weight-1 tenant under contention, and an idle
    tenant's share is redistributed, not banked).  ``max_queue``
    bounds this tenant's router queue (0 = unbounded; ``reject``
    surfaces ``QueueFullError`` PDT-E017).  ``slo`` arms per-tenant
    objectives (spec string or ``SLOSpec`` list) over the tenant's own
    registry, judged from the router-observed queue wait and finish
    reasons — read them back via ``FleetRouter.slo_status()``.
    """

    def __init__(self, name, *, weight=1.0, priority=0, max_queue=0,
                 queue_policy="reject", slo=None):
        self.name = str(name)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0, "
                             f"got {weight}")
        self.priority = int(priority)
        self.max_queue = int(max_queue)
        self.queue_policy = str(queue_policy)
        if self.queue_policy not in ("reject", "block"):
            raise ValueError(f"tenant {name!r}: queue_policy must be "
                             f"'reject' or 'block', got {queue_policy!r}")
        self.slo = slo


class _RouterReq:
    __slots__ = ("rid", "tenant", "prompt", "max_new_tokens", "eos",
                 "deadline", "state", "replica", "requeues", "enq_t",
                 "cost")

    def __init__(self, rid, tenant, prompt, max_new_tokens, eos,
                 deadline, enq_t):
        self.rid = rid
        self.tenant = tenant
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos = eos
        self.deadline = deadline      # absolute clock() seconds | None
        self.state = "pending"        # pending | placed | done
        self.replica = None
        self.requeues = 0
        self.enq_t = enq_t
        # stride-scheduling cost: service demand in tokens
        self.cost = int(prompt.size) + int(max_new_tokens)


class _Replica:
    """Router-side handle: engine + membership state.

    ``rids`` is an insertion-ordered dict used as an ordered set — on
    death the requeue preserves original placement order, which keeps
    the drill deterministic."""

    __slots__ = ("name", "engine", "index", "state", "gen", "last_beat",
                 "rids", "scaled_out", "limits")

    def __init__(self, name, engine, index, state, limits):
        self.name = str(name)
        self.engine = engine
        self.index = int(index)
        self.state = state            # live | standby | draining | dead
        self.gen = 0
        self.last_beat = 0.0
        self.rids: dict = {}          # rid -> True, insertion-ordered
        self.scaled_out = False
        self.limits = limits


_STATE_CODE = {"standby": 0, "live": 1, "draining": 2, "dead": 3,
               "lameduck": 4}


class FleetRouter:
    """Spread serving traffic over N engine replicas; survive any of
    them dying.  See the module docstring for the placement, QoS,
    failure and scaling semantics.

    ``replicas`` is an int (the router builds that many
    ``ContinuousBatchingEngine(model, **replica_kwargs)`` workers —
    same-geometry replicas share the model's compiled serving
    programs) or a list of prebuilt engines / ``DisaggServer`` /
    ``RpcReplica`` objects; ``standby`` likewise (kwargs default to
    ``replica_kwargs``).  Policy kwargs follow the engine convention:
    ``None`` falls back to the ``serving_fleet_*`` flags.  ``clock``
    (tests) replaces ``time.monotonic`` for deterministic deadline /
    heartbeat / SLO drills."""

    def __init__(self, model=None, *, replicas=None, replica_kwargs=None,
                 standby=0, standby_kwargs=None, tenants=None,
                 default_tenant="default", affinity=None,
                 fleet_slo=None, heartbeat_timeout_ms=None,
                 dispatch_retries=None, scaleout_timeout_ms=None,
                 scalein_hold_s=None, watchdog_ms=None,
                 max_queue=None, queue_policy=None,
                 default_deadline_ms=None, migration=None,
                 lameduck_ms=None, migration_retries=None, clock=None):
        from ..core import state as _state
        self._clock = time.monotonic if clock is None else clock
        self.affinity = bool(_state.get_flag("serving_fleet_affinity")
                             if affinity is None else affinity)
        hb = (_state.get_flag("serving_fleet_heartbeat_ms")
              if heartbeat_timeout_ms is None else heartbeat_timeout_ms)
        self.heartbeat_timeout_ms = float(hb)
        dr = (_state.get_flag("serving_fleet_dispatch_retries")
              if dispatch_retries is None else dispatch_retries)
        self.dispatch_retries = int(dr)
        so = (_state.get_flag("serving_fleet_scaleout_timeout_ms")
              if scaleout_timeout_ms is None else scaleout_timeout_ms)
        self.scaleout_timeout_ms = float(so)
        sh = (_state.get_flag("serving_fleet_scalein_hold_s")
              if scalein_hold_s is None else scalein_hold_s)
        self.scalein_hold_s = float(sh)
        self.watchdog_ms = float(_state.get_flag("watchdog_stall_ms")
                                 if watchdog_ms is None else watchdog_ms)
        self.max_queue = int(_state.get_flag("serving_max_queue")
                             if max_queue is None else max_queue)
        self.queue_policy = str(_state.get_flag("serving_queue_policy")
                                if queue_policy is None else queue_policy)
        self.default_deadline_ms = float(
            _state.get_flag("serving_deadline_ms")
            if default_deadline_ms is None else default_deadline_ms)
        self.migration = bool(_state.get_flag("serving_migration")
                              if migration is None else migration)
        self.lameduck_ms = float(_state.get_flag("serving_lameduck_ms")
                                 if lameduck_ms is None else lameduck_ms)
        self.migration_retries = int(
            _state.get_flag("serving_migration_retries")
            if migration_retries is None else migration_retries)
        self._transport = KVPageTransport(retries=self.migration_retries)

        # ------------------------------------------------- replicas --
        if replicas is None:
            replicas = int(_state.get_flag("serving_fleet_replicas"))
        rkw = dict(replica_kwargs or {})

        def _build(n, kw):
            if not n:
                return []
            if model is None:
                raise ValueError(
                    "FleetRouter needs model= to build replicas from "
                    "an int; pass prebuilt engines otherwise")
            return [ContinuousBatchingEngine(model, **kw)
                    for _ in range(int(n))]

        live = (_build(replicas, rkw) if isinstance(replicas, int)
                else list(replicas))
        if not live:
            raise ValueError("FleetRouter needs at least one replica")
        skw = dict(standby_kwargs if standby_kwargs is not None else rkw)
        stand = (_build(standby, skw) if isinstance(standby, int)
                 else list(standby))
        self._replicas: list[_Replica] = []
        now = self._clock()
        for i, eng in enumerate(live + stand):
            rep = _Replica(f"r{i}", eng, i,
                           "live" if i < len(live) else "standby",
                           _probe_limits(eng))
            rep.last_beat = now
            self._replicas.append(rep)
        self._base_live = len(live)

        # -------------------------------------------------- tenants --
        self.default_tenant = str(default_tenant)
        self._tenants: dict = {}
        self._torder: dict = {}
        for spec in (tenants or []):
            self._add_tenant(spec)
        if self.default_tenant not in self._tenants:
            self._add_tenant(TenantSpec(self.default_tenant))
        self._tq: dict = {n: deque() for n in self._tenants}
        self._vt: dict = {n: 0.0 for n in self._tenants}

        # -------------------------------------------- observability --
        self._registry = _ObsRegistry("serving_router")
        self._c = RegistryCounters(self._registry, (
            "admitted", "placed", "finished", "rejected", "timeouts",
            "requeues", "retries", "deaths", "scaleouts", "scaleins",
            "scaleout_failures", "affinity_hits", "affinity_spills",
            "migrations", "migrated_pages", "migration_retries",
            "migration_failures", "lameducks"),
            prefix="router")
        self._h_queue = self._registry.histogram(
            "serving.queue_ms", "router-queue wait: admission -> "
            "placement on a replica", buckets=LATENCY_BUCKETS_MS)
        self._fin_c: dict = {}
        for rep in self._replicas:
            self._reg_replica_gauges(rep)
        self._g_live = self._registry.gauge(
            "router.replicas_live", "replicas taking placements")
        self._g_live.set_function(
            lambda: sum(1 for r in self._replicas if r.state == "live"))
        self._g_queue = self._registry.gauge(
            "router.queue_depth", "requests waiting for placement")
        self._g_queue.set_function(
            lambda: sum(len(q) for q in self._tq.values()))

        # per-tenant registries + SLO engines (fed by _finish)
        self._treg: dict = {}
        self._tslo: dict = {}
        self._tfin: dict = {}
        self._th_queue: dict = {}
        for name, spec in self._tenants.items():
            reg = _ObsRegistry(f"serving_router_tenant_{name}")
            self._treg[name] = reg
            self._th_queue[name] = reg.histogram(
                "serving.queue_ms", "tenant router-queue wait",
                buckets=LATENCY_BUCKETS_MS)
            self._tfin[name] = {}
            if spec.slo is not None:
                self._tslo[name] = _slo_mod.SLOEngine(
                    reg, _slo_mod.parse_slo(spec.slo),
                    clock=self._clock)

        # fleet SLO -> scale-out trigger
        self._fleet_slo = None
        if fleet_slo is None:
            fleet_slo = _state.get_flag("serving_fleet_slo")
        if fleet_slo:
            self._fleet_slo = _slo_mod.SLOEngine(
                self._registry, _slo_mod.parse_slo(fleet_slo),
                clock=self._clock, on_breach=self._on_fleet_breach)

        # ------------------------------------------------- bookkeeping
        self._reqs: dict = {}
        self._finalized: list = []
        self._next_rid = 0
        self._gen = 0
        self._rr = -1                 # round-robin cursor
        self._breached = False
        self._last_breach_t = None
        self._next_scaleout_t = float("-inf")
        self._scaleout_cooldown_s = 1.0
        self._tick = 0
        self._preempt_seen = False

    # ------------------------------------------------------ tenants --
    def _add_tenant(self, spec):
        if not isinstance(spec, TenantSpec):
            spec = TenantSpec(str(spec))
        self._tenants[spec.name] = spec
        self._torder[spec.name] = len(self._torder)

    # ---------------------------------------------------- admission --
    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    tenant=None, request_id=None, deadline_ms=None):
        """Queue one request under ``tenant``'s QoS contract; returns
        the request id.  Eagerly rejects what no replica could ever
        serve (``PageBudgetError`` PDT-E016) and what the queue bounds
        refuse (``QueueFullError`` PDT-E017, policy ``reject``)."""
        prompt = np.asarray(
            prompt.numpy() if isinstance(prompt, Tensor) else prompt,
            np.int32).reshape(-1)
        tname = self.default_tenant if tenant is None else str(tenant)
        if tname not in self._tenants:
            # unknown tenants ride the default contract under their
            # own name (fair share still separates them)
            self._add_tenant(TenantSpec(tname))
            self._tq[tname] = deque()
            self._vt[tname] = 0.0
            reg = _ObsRegistry(f"serving_router_tenant_{tname}")
            self._treg[tname] = reg
            self._th_queue[tname] = reg.histogram(
                "serving.queue_ms", "tenant router-queue wait",
                buckets=LATENCY_BUCKETS_MS)
            self._tfin[tname] = {}
        spec = self._tenants[tname]
        total = prompt.size + int(max_new_tokens)
        if not any(self._fits_limits(rep.limits, prompt.size,
                                     max_new_tokens)
                   for rep in self._replicas if rep.state != "dead"):
            self._c["rejected"] += 1
            lim = max((rep.limits["total_pages"] - 1)
                      * rep.limits["page_size"]
                      for rep in self._replicas if rep.state != "dead")
            raise PageBudgetError(
                f"request needs {total} tokens but no fleet replica "
                f"can hold more than {lim}; raise total_pages or "
                f"lower max_new_tokens [{PageBudgetError.error_code}]")
        qlen = sum(len(q) for q in self._tq.values())
        if (self.max_queue and qlen >= self.max_queue) or (
                spec.max_queue
                and len(self._tq[tname]) >= spec.max_queue):
            policy = (spec.queue_policy if spec.max_queue
                      and len(self._tq[tname]) >= spec.max_queue
                      else self.queue_policy)
            if policy == "reject":
                self._c["rejected"] += 1
                raise QueueFullError(
                    f"router admission queue full (fleet {qlen}, "
                    f"tenant {tname!r} {len(self._tq[tname])}); shed "
                    f"load or use queue_policy='block' "
                    f"[{QueueFullError.error_code}]")
            for _ in range(1_000_000):
                room = (not self.max_queue or sum(
                    len(q) for q in self._tq.values()) < self.max_queue)
                troom = (not spec.max_queue
                         or len(self._tq[tname]) < spec.max_queue)
                if (room and troom) or not self.has_work:
                    break
                self._finalized.extend(self.step())
            else:
                raise RuntimeError("queue_policy='block': fleet made "
                                   "no progress draining the queue")
        if request_id is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = request_id
            if isinstance(rid, int):
                self._next_rid = max(self._next_rid, rid + 1)
            if rid in self._reqs and self._reqs[rid].state != "done":
                raise ValueError(f"request_id {rid!r} already in flight")
        dl_ms = (self.default_deadline_ms
                 if deadline_ms is None else float(deadline_ms))
        now = self._clock()
        deadline = (now + dl_ms / 1e3) if dl_ms else None
        rs = _RouterReq(rid, tname, prompt, max_new_tokens,
                        eos_token_id, deadline, now)
        self._reqs[rid] = rs
        if not self._tq[tname]:
            # start-time fairness: a returning tenant joins at the
            # current virtual time instead of cashing in banked lag
            active = [self._vt[t] for t, q in self._tq.items() if q]
            if active:
                self._vt[tname] = max(self._vt[tname], min(active))
        self._tq[tname].append(rs)
        self._c["admitted"] += 1
        _events.emit("router.enqueued", rid=rid, tenant=tname,
                     prompt_len=int(prompt.size))
        return rid

    def cancel(self, rid) -> bool:
        """Cancel a queued or placed request; the ``cancelled``
        completion surfaces from the next :meth:`step`."""
        rs = self._reqs.get(rid)
        if rs is None or rs.state == "done":
            return False
        if rs.state == "pending":
            self._tq[rs.tenant].remove(rs)
            self._finalize_local(rs, "cancelled")
            return True
        rep = self._by_name(rs.replica)
        if rep is not None and rep.state != "dead":
            return bool(rep.engine.cancel(rid))
        return False

    # ------------------------------------------------------ stepping --
    def step(self):
        """One fleet tick: failure detection -> QoS placement ->
        watchdog-armed replica steps -> SLO-driven scaling.  Returns
        the completions that surfaced this tick."""
        now = self._clock()
        self._tick += 1
        if (self.migration and not self._preempt_seen
                and _preempt.requested()):
            self._preempt_seen = True
            self._on_preempt()
        out = list(self._finalized)
        self._finalized.clear()
        self._check_replicas(now)
        out.extend(self._place(now))
        for rep in list(self._replicas):
            if rep.state not in ("live", "draining", "lameduck"):
                continue
            token = _watchdog.arm("router.step", self.watchdog_ms,
                                  key=rep.name,
                                  interrupt_exc=EngineStallError)
            try:
                with _tracing.span("router.step", replica=rep.name):
                    cs = rep.engine.step()
            except EngineStallError as e:
                # the watchdog already captured stacks + the flight
                # record; the death record is that one, not a second
                self._kill(rep, "stall", error=e, flight=False)
                continue
            except ConnectionError as e:
                self._kill(rep, "connection", error=e)
                continue
            finally:
                token.disarm()
            rep.last_beat = self._clock()
            for c in cs:
                done = self._finish(c, rep, now)
                if done is not None:
                    out.append(done)
        self._maybe_scale(now)
        return out

    def run(self, max_steps=10000):
        """Drain: step until every request completes.  Returns
        ``{request_id: CompletedRequest}`` in completion order."""
        import warnings
        done = {}
        for _ in range(max_steps):
            if not self.has_work:
                break
            for c in self.step():
                done[c.request_id] = c
        if self.has_work:
            warnings.warn(
                f"FleetRouter.run: step budget ({max_steps}) exhausted "
                f"with requests still in flight",
                RuntimeWarning, stacklevel=2)
        return done

    @property
    def has_work(self):
        return (any(self._tq.values()) or bool(self._finalized)
                or any(rep.rids
                       or (rep.state in ("live", "draining", "lameduck")
                           and rep.engine.has_work)
                       for rep in self._replicas
                       if rep.state != "dead"))

    # ---------------------------------------------- failure handling --
    def _check_replicas(self, now):
        for rep in list(self._replicas):
            if rep.state not in ("live", "draining", "lameduck"):
                continue
            if faults.check(SITE_ROUTER_REPLICA_LOST, key=rep.name):
                self._kill(rep, "fault_drill")
            elif (self.heartbeat_timeout_ms
                  and (now - rep.last_beat) * 1e3
                  > self.heartbeat_timeout_ms):
                self._kill(rep, "heartbeat_timeout")
            elif (rep.state == "live" and self.migration
                  and self.lameduck_ms
                  and (now - rep.last_beat) * 1e3 > self.lameduck_ms
                  and len(self._live()) > 1):
                # degraded but not yet dead: stop feeding it and move
                # its residents out warm before the heartbeat verdict
                self._lameduck(rep, "degraded_heartbeat")

    def _kill(self, rep, reason, error=None, flight=True):
        """Declare ``rep`` dead: generation bump, ONE coded flight
        record, queued + in-flight requests requeued to survivors at
        the front of their tenant queues (original placement order
        preserved — with greedy decode that makes the faulted run
        bitwise vs unfaulted)."""
        if rep.state == "dead":
            return
        rep.state = "dead"
        self._gen += 1
        rep.gen = self._gen
        affected = []
        for rid in rep.rids:
            rs = self._reqs.get(rid)
            if rs is None or rs.state != "placed":
                continue
            rs.state = "pending"
            rs.replica = None
            rs.requeues += 1
            affected.append(rs)
        rep.rids.clear()
        for rs in reversed(affected):
            self._tq[rs.tenant].appendleft(rs)
        self._c["requeues"] += len(affected)
        self._c["deaths"] += 1
        err = error if error is not None else ReplicaLostError(
            f"replica {rep.name!r} declared dead ({reason}); "
            f"{len(affected)} request(s) requeued to survivors "
            f"[{ReplicaLostError.error_code}]")
        if flight:
            _flight.dump("router_replica_lost", error=err, extra={
                "replica": rep.name, "reason": reason,
                "generation": self._gen, "requeued": len(affected)})
        _events.emit("router.replica_dead", replica=rep.name,
                     reason=reason, requeued=len(affected),
                     generation=self._gen)

    # ------------------------------------------------ live migration --
    def drain(self, name) -> bool:
        """Gracefully drain replica ``name``: placements stop NOW and,
        with ``migration`` on, resident requests migrate warm to the
        surviving live replicas instead of running to completion —
        scale-in latency becomes a transfer, not a tail decode.  With
        migration off (or no survivor) residents finish in place.  The
        emptied replica returns to ``standby`` (cache intact).
        Returns False for unknown / already-draining / dead replicas."""
        rep = self._by_name(str(name))
        if rep is None or rep.state not in ("live", "lameduck"):
            return False
        rep.state = "draining"
        self._gen += 1
        rep.gen = self._gen
        _events.emit("router.draining", replica=rep.name,
                     reason="drain", generation=self._gen)
        if self.migration:
            self._migrate_replica(rep)
        return True

    def _lameduck(self, rep, reason):
        """Planned-preemption / degraded-replica disposition: stop new
        placements, migrate residents warm, keep stepping what stays
        (a failed migration leaves the request serving on the duck)."""
        if rep.state != "live":
            return
        rep.state = "lameduck"
        self._gen += 1
        rep.gen = self._gen
        self._c["lameducks"] += 1
        _events.emit("router.lameduck", replica=rep.name,
                     reason=reason, generation=self._gen)
        if self.migration:
            self._migrate_replica(rep)

    def _on_preempt(self):
        """The eviction notice arrived (``resilience.preempt``): lame-
        duck the elastically scaled-out replicas first, else the last
        live one — never the last replica standing, which must keep
        serving until the process actually dies."""
        live = self._live()
        victims = [r for r in live if r.scaled_out]
        if not victims and len(live) > 1:
            victims = [live[-1]]
        for rep in victims:
            if len(self._live()) <= 1:
                break
            self._lameduck(rep, "preempt")

    def _migrate_replica(self, rep):
        for rid in list(rep.rids):
            self._migrate_one(rep, rid)
            if rep.state == "dead":
                break

    def _pick_migration_dst(self, rep, rs):
        cands = [r for r in self._live()
                 if r is not rep
                 and self._fits_limits(r.limits, rs.prompt.size,
                                       rs.max_new_tokens)]
        if not cands:
            return None
        if self.affinity:
            hits = {}
            for r in cands:
                try:
                    hits[r.name] = int(
                        r.engine.cached_prefix_tokens(rs.prompt))
                except (ConnectionError, AttributeError):
                    hits[r.name] = 0
            best = max(cands, key=lambda r: (hits[r.name],
                                             -len(r.rids), -r.index))
            if hits[best.name] > 0:
                return best
        return min(cands, key=lambda r: (len(r.rids), r.index))

    def _migrate_one(self, rep, rid) -> bool:
        """Move one resident request off ``rep``: snapshot -> ship
        (bounded retry) -> restore on the destination -> discard at
        the source.  Failure dispositions: a torn snapshot (CRC
        mismatch, ``MigrationError`` unretried) leaves the request
        serving at the source; an exhausted transfer budget falls back
        to the PR17 cold requeue with exactly ONE coded flight record;
        a raced ``cancel`` keeps the request at the source so its
        sweep emits the single ``cancelled`` completion and the
        destination restore is dropped."""
        rs = self._reqs.get(rid)
        if rs is None or rs.state != "placed":
            return False
        try:
            payload = rep.engine.snapshot_request(rid)
        except ConnectionError as e:
            self._kill(rep, "snapshot", error=e)
            return False
        except (KeyError, ValueError, AttributeError):
            # finished/cancelling under us, or a replica kind without
            # the snapshot surface (DisaggServer): it finishes in place
            return False
        dst = self._pick_migration_dst(rep, rs)
        if dst is None:
            return False           # no survivor fits: retry next tick

        def on_retry(_exc, _attempt):
            self._c["migration_retries"] += 1

        try:
            with _tracing.span("router.migrate", rid=str(rid),
                               src=rep.name, dst=dst.name):
                got, nbytes = self._transport.ship_snapshot(
                    payload, dst.engine, on_retry=on_retry)
        except MigrationError as e:
            # torn snapshot: rejected AT RESTORE — nothing landed on
            # the destination and the source never stopped serving
            self._c["migration_failures"] += 1
            _flight.dump("router_migration_torn", error=e, extra={
                "rid": str(rid), "src": rep.name, "dst": dst.name,
                "fallback": "source_keeps"})
            _events.emit("router.migration_torn", rid=rid,
                         src=rep.name, dst=dst.name)
            return False
        except ConnectionError as e:
            # transfer budget exhausted: cold requeue (PR17) — a
            # from-scratch re-prefill that greedy determinism keeps
            # bitwise; prefill work is lost, the request is not
            self._c["migration_failures"] += 1
            err = MigrationError(
                f"migrating request {rid!r} from {rep.name!r} to "
                f"{dst.name!r} failed past the retry budget "
                f"({self.migration_retries}): {e}; falling back to "
                f"cold requeue [{MigrationError.error_code}]")
            _flight.dump("router_migration_failed", error=err, extra={
                "rid": str(rid), "src": rep.name, "dst": dst.name,
                "retries": self.migration_retries,
                "fallback": "cold_requeue"})
            _events.emit("router.migration_failed", rid=rid,
                         src=rep.name, dst=dst.name)
            self._cold_requeue(rep, rs)
            return False
        if got is None:
            return False     # destination full this tick: retry later
        rep.rids.pop(rid, None)
        try:
            kept = rep.engine.discard_request(rid)
        except ConnectionError as e:
            # source died right after the copy landed; the migrated
            # copy is authoritative, _kill requeues only the rest
            self._kill(rep, "migration_discard", error=e)
            kept = True
        except KeyError:
            kept = True
        if kept is False:
            # cancel raced the migration: the SOURCE sweep owns the
            # single "cancelled" completion; drop the restored copy
            try:
                dst.engine.discard_request(rid)
            except (KeyError, ConnectionError):
                pass
            if rep.state != "dead":
                rep.rids[rid] = True
            return False
        dst.rids[rid] = True
        rs.replica = dst.name
        pages = int(payload.get("n_pages", 0) or 0)
        self._c["migrations"] += 1
        self._c["migrated_pages"] += pages
        _events.emit("router.migrated", rid=rid, src=rep.name,
                     dst=dst.name, phase=str(payload.get("phase")),
                     pages=pages, bytes=int(nbytes))
        return True

    def _cold_requeue(self, rep, rs):
        """Migration fallback: release the source copy and put the
        request back at the front of its tenant queue for a cold
        re-prefill (``requeue=True`` on the next placement keeps the
        demand counted once)."""
        try:
            kept = rep.engine.discard_request(rs.rid)
        except ConnectionError as e:
            self._kill(rep, "migration", error=e)   # requeues it too
            return
        except KeyError:
            kept = True
        if kept is False:
            return       # cancel raced: the source sweep finalizes it
        rep.rids.pop(rs.rid, None)
        rs.state = "pending"
        rs.replica = None
        rs.requeues += 1
        self._tq[rs.tenant].appendleft(rs)
        self._c["requeues"] += 1
        _events.emit("router.requeued", rid=rs.rid, replica=rep.name,
                     reason="migration_failed")

    # ----------------------------------------------------- placement --
    def _remaining_ms(self, rs):
        if rs.deadline is None:
            return None
        return (rs.deadline - self._clock()) * 1e3

    @staticmethod
    def _fits_limits(lim, prompt_len, max_new_tokens):
        total = int(prompt_len) + int(max_new_tokens)
        if total > lim["max_seq_len"]:
            return False
        need = -(-total // lim["page_size"])
        return need <= lim["total_pages"] - 1

    def _cap(self, rep):
        """Placement budget this tick: resident slots plus a one-deep
        admission queue per slot — enough to keep the mixed step fed
        without parking whole tenants on one replica's queue (parked
        requests cannot be fair-share reordered)."""
        return max(0, 2 * rep.limits["max_slots"] - len(rep.rids))

    def _live(self):
        return [r for r in self._replicas if r.state == "live"]

    def _pick_tenant(self):
        """Strict priority across classes, weighted virtual-time fair
        share within a class, admission order as the final tie."""
        best = None
        for name, q in self._tq.items():
            if not q:
                continue
            spec = self._tenants[name]
            key = (spec.priority, self._vt[name], self._torder[name])
            if best is None or key < best[0]:
                best = (key, name)
        return None if best is None else best[1]

    def _pick_replica(self, rs, caps):
        cands = [rep for rep in self._live()
                 if caps.get(rep.name, 0) > 0
                 and self._fits_limits(rep.limits, rs.prompt.size,
                                       rs.max_new_tokens)]
        if not cands:
            return None
        if not self.affinity:
            self._rr += 1
            return cands[self._rr % len(cands)]
        hits = {}
        for rep in cands:
            try:
                hits[rep.name] = int(
                    rep.engine.cached_prefix_tokens(rs.prompt))
            except ConnectionError:
                hits[rep.name] = 0   # suspect replica: heartbeat will
                # time out / its step will fail; scoring must not kill
        best = max(cands, key=lambda rep: (hits[rep.name],
                                           -len(rep.rids), -rep.index))
        if hits[best.name] > 0:
            self._c["affinity_hits"] += 1
            return best
        self._c["affinity_spills"] += 1
        return min(cands, key=lambda rep: (len(rep.rids), rep.index))

    def _place(self, now):
        out = []
        if not self._live() and any(self._tq.values()):
            # total fleet loss: failover to a standby immediately (no
            # SLO verdict needed), else fail coded instead of hanging
            if not self._scale_out(now, reason="failover"):
                if not any(r.state in ("live", "draining")
                           for r in self._replicas):
                    raise ReplicaLostError(
                        "every fleet replica is dead with requests "
                        "still queued; add standby replicas for "
                        f"failover [{ReplicaLostError.error_code}]")
        caps = {rep.name: self._cap(rep) for rep in self._live()}
        total = sum(caps.values())
        while total > 0:
            tname = self._pick_tenant()
            if tname is None:
                break
            rs = self._tq[tname].popleft()
            if rs.deadline is not None and now >= rs.deadline:
                out.append(self._finalize_local(rs, "timeout"))
                continue
            rep = self._pick_replica(rs, caps)
            if rep is None:
                self._tq[tname].appendleft(rs)
                break
            if self._dispatch_place(rep, rs):
                caps[rep.name] -= 1
                total -= 1
                self._vt[tname] += rs.cost / self._tenants[tname].weight
            else:
                # placement killed the replica; requeue at the front
                # and re-derive the budget from the survivors
                self._tq[tname].appendleft(rs)
                caps = {rep.name: self._cap(rep)
                        for rep in self._live()}
                total = sum(caps.values())
        return out

    def _dispatch_place(self, rep, rs):
        def call():
            faults.maybe_raise(SITE_ROUTER_DISPATCH_TRANSIENT,
                               str(rs.rid))
            return rep.engine.add_request(
                rs.prompt, rs.max_new_tokens, eos_token_id=rs.eos,
                request_id=rs.rid, deadline_ms=self._remaining_ms(rs),
                requeue=rs.requeues > 0)

        def on_retry(_exc, _attempt):
            self._c["retries"] += 1

        try:
            with _tracing.span("router.place", rid=str(rs.rid),
                               replica=rep.name):
                call_out = retry_call(
                    call, max_attempts=self.dispatch_retries + 1,
                    base_delay=0.005, max_delay=0.05,
                    retry_on=(ConnectionError,), on_retry=on_retry)
        except ConnectionError as e:
            self._kill(rep, "dispatch", error=e)
            return False
        del call_out
        rs.state = "placed"
        rs.replica = rep.name
        rep.rids[rs.rid] = True
        self._c["placed"] += 1
        if _obs_metrics.enabled():
            wait = (self._clock() - rs.enq_t) * 1e3
            self._h_queue.observe(wait)
            self._th_queue[rs.tenant].observe(wait)
        _events.emit("router.placed", rid=rs.rid, replica=rep.name,
                     tenant=rs.tenant, requeue=rs.requeues)
        return True

    # -------------------------------------------------- completions --
    def _finish(self, c, rep, now):
        rs = self._reqs.get(c.request_id)
        rep.rids.pop(c.request_id, None)
        if rs is None or rs.state == "done":
            return None    # late echo of a request finalized elsewhere
        rs.state = "done"
        self._c["finished"] += 1
        self._observe_finish(rs, c.finish_reason)
        return c

    def _finalize_local(self, rs, reason):
        """Finalize a request the replicas never completed (timeout in
        the router queue, cancel while pending)."""
        rs.state = "done"
        self._c["finished"] += 1
        if reason == "timeout":
            self._c["timeouts"] += 1
        self._observe_finish(rs, reason)
        return CompletedRequest(rs.rid, rs.prompt,
                                np.zeros(0, np.int32), reason)

    def _fin_counter(self, cache, registry, reason):
        c = cache.get(reason)
        if c is None:
            c = registry.counter(
                "serving.finished", "requests retired by reason",
                labels={"reason": reason}, always=True)
            cache[reason] = c
        return c

    def _observe_finish(self, rs, reason):
        self._fin_counter(self._fin_c, self._registry, reason).inc()
        self._fin_counter(self._tfin[rs.tenant], self._treg[rs.tenant],
                          reason).inc()
        tslo = self._tslo.get(rs.tenant)
        if tslo is not None:
            tslo.maybe_evaluate(self._clock())
        _events.emit("router.finished", rid=rs.rid, tenant=rs.tenant,
                     reason=reason, requeues=rs.requeues)

    # ------------------------------------------------------- scaling --
    def _on_fleet_breach(self, status):
        """Breach hook (fires once per not-breached -> breached
        transition): the postmortem flight record, like the engine's
        — the scale-out decision itself rides the latched status."""
        _flight.dump("fleet_slo_breach", extra=dict(status))

    def _maybe_scale(self, now):
        if self._fleet_slo is not None:
            st = self._fleet_slo.maybe_evaluate(now)
            if st is not None:
                self._breached = any(s["breached"] for s in st)
        if self._breached:
            self._last_breach_t = now
            if (now >= self._next_scaleout_t
                    and not any(r.state == "draining"
                                for r in self._replicas)):
                self._scale_out(now, reason="slo_breach")
        elif (self._last_breach_t is not None
              and now - self._last_breach_t >= self.scalein_hold_s):
            self._scale_in(now)
        # drain completion: a draining/lame-duck replica with no work
        # returns to standby (cache intact — a re-admission is
        # part-warm); stragglers (a full destination, a skipped
        # cancel) get another migration attempt each tick first
        for rep in self._replicas:
            if rep.state not in ("draining", "lameduck"):
                continue
            if rep.rids and self.migration:
                self._migrate_replica(rep)
            if not rep.rids and not rep.engine.has_work:
                rep.state = "standby"
                rep.scaled_out = False
                self._gen += 1
                rep.gen = self._gen
                self._c["scaleins"] += 1
                _events.emit("router.scalein", replica=rep.name,
                             generation=self._gen)

    def _scale_out(self, now, reason):
        rep = next((r for r in self._replicas if r.state == "standby"),
                   None)
        if rep is None:
            self._next_scaleout_t = now + self._scaleout_cooldown_s
            _events.emit("router.scaleout_exhausted", reason=reason)
            return False
        token = _watchdog.arm("router.scaleout",
                              self.scaleout_timeout_ms, key=rep.name,
                              interrupt_exc=EngineStallError)
        try:
            with _tracing.span("router.scaleout", replica=rep.name,
                               reason=reason):
                # the drill body: a wedged standby (hung weight load,
                # dead host) must surface coded, not hang the router
                simulated_stall(rep.name,
                                site=SITE_ROUTER_SCALEOUT_STALL)
                rep.state = "live"
                rep.scaled_out = reason != "failover"
                rep.last_beat = self._clock()
                self._gen += 1
                rep.gen = self._gen
        except Exception as e:
            token.disarm()
            self._c["scaleout_failures"] += 1
            self._next_scaleout_t = now + self._scaleout_cooldown_s
            _events.emit(
                "router.scaleout_failed", replica=rep.name,
                reason=reason, error=f"{type(e).__name__}: {e}",
                code=getattr(type(e), "error_code", None),
                flight=token.dump_path)
            return False
        finally:
            token.disarm()
        self._c["scaleouts"] += 1
        self._next_scaleout_t = now + self._scaleout_cooldown_s
        _events.emit("router.scaleout", replica=rep.name,
                     reason=reason, generation=self._gen)
        return True

    def _scale_in(self, now):
        rep = next((r for r in reversed(self._replicas)
                    if r.state == "live" and r.scaled_out), None)
        if rep is None:
            return
        if len(self._live()) <= max(1, self._base_live):
            return
        rep.state = "draining"
        _events.emit("router.draining", replica=rep.name)
        if self.migration:
            # scale-in without waiting out the tail: move the
            # residents warm and the replica parks next tick
            self._migrate_replica(rep)

    # ------------------------------------------------ observability --
    def _reg_replica_gauges(self, rep):
        g = self._registry.gauge(
            "router.replica_load", "router-known in-flight requests",
            labels={"replica": rep.name})
        g.set_function(lambda rep=rep: len(rep.rids))
        g = self._registry.gauge(
            "router.replica_state",
            "0=standby 1=live 2=draining 3=dead 4=lameduck",
            labels={"replica": rep.name})
        g.set_function(lambda rep=rep: _STATE_CODE[rep.state])
        g = self._registry.gauge(
            "router.replica_generation",
            "fleet generation at this replica's last state change",
            labels={"replica": rep.name})
        g.set_function(lambda rep=rep: rep.gen)

    def _by_name(self, name):
        for rep in self._replicas:
            if rep.name == name:
                return rep
        return None

    @property
    def stats(self):
        """Router counters plus live fleet gauges (always on — the
        ``stats`` contract survives ``PDTPU_METRICS=off``)."""
        d = self._c.as_dict()
        d["queue_depth"] = sum(len(q) for q in self._tq.values())
        d["replicas_live"] = sum(
            1 for r in self._replicas if r.state == "live")
        d["replicas_standby"] = sum(
            1 for r in self._replicas if r.state == "standby")
        d["replicas_draining"] = sum(
            1 for r in self._replicas if r.state == "draining")
        d["replicas_dead"] = sum(
            1 for r in self._replicas if r.state == "dead")
        d["replicas_lameduck"] = sum(
            1 for r in self._replicas if r.state == "lameduck")
        d["generation"] = self._gen
        d["tenants"] = {
            name: {"queued": len(self._tq[name]),
                   "weight": self._tenants[name].weight,
                   "priority": self._tenants[name].priority}
            for name in self._tenants}
        return d

    def metrics(self) -> dict:
        """The router registry snapshot: counters, the fleet queue-ms
        histogram, per-replica labeled gauges.  Per-request timelines
        live on the replica engines (``router.replica('r0').
        metrics()``)."""
        return self._registry.snapshot()

    def tenant_metrics(self, tenant) -> dict:
        """One tenant's registry snapshot (queue-ms + finish reasons —
        the inputs its per-tenant SLO is judged from)."""
        return self._treg[str(tenant)].snapshot()

    def render_prometheus(self) -> str:
        return self._registry.render_prometheus()

    def replica(self, name):
        """The replica engine registered under ``name`` (``r0``...)."""
        rep = self._by_name(str(name))
        return None if rep is None else rep.engine

    def replica_states(self) -> dict:
        return {rep.name: rep.state for rep in self._replicas}

    def slo_status(self) -> dict:
        """Fleet-wide SLO picture: the fleet specs (scale-out's
        inputs), per-tenant specs, and every replica's own engine SLO
        status, keyed by replica name."""
        out = {"fleet": ([] if self._fleet_slo is None
                         else self._fleet_slo.status()),
               "tenants": {name: eng.status()
                           for name, eng in self._tslo.items()},
               "replicas": {}}
        for rep in self._replicas:
            if rep.state == "dead":
                continue
            try:
                out["replicas"][rep.name] = rep.engine.slo_status()
            except (ConnectionError, AttributeError):
                out["replicas"][rep.name] = []
        return out

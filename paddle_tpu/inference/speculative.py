"""Speculative decoding: draft-propose / ragged-verify (ISSUE 9).

Decode throughput is bounded by one target-model dispatch per token —
but the ragged paged-attention entry (PAPERS.md #1) already serves
arbitrary per-sequence ``q_lens`` in a single call, which is exactly
"verify K draft tokens per sequence".  The engine's speculative mode
submits, per decode step and per slot, the slot's current token plus K
proposed tokens as ONE ragged segment (``q_lens = K+1``) through the
existing mixed program — no new kernel, no recompile per K (lengths are
data) — and accepts the longest draft prefix the target model agrees
with.

Acceptance rule (greedy, the default):

* the verify dispatch returns the target's greedy token after EVERY
  position of the segment (``models.generation.verify_argmax``);
* with committed position ``P``, current token ``t0`` and drafts
  ``d1..dK``: let ``g_i`` be the target's pick after ``t0 d1..d_{i-1}``
  and ``m`` the count of leading matches (``d_i == g_i``).  The step
  emits ``g_1..g_{m+1}`` — the ``m`` agreed drafts plus the target's
  own next token, which is free (its logits row was already computed).
  Every emitted token is BY CONSTRUCTION the token plain greedy decode
  would have produced on the same committed context, so speculative
  greedy output is bitwise-identical to ``spec_decode=off``; drafts can
  only change HOW MANY tokens a step emits (1..K+1), never which.
* KV ROLLBACK is positional: the verify dispatch wrote K+1 tokens' KV
  at positions ``P..P+K``, but the slot's ``len_written`` advances only
  past the accepted prefix (``P+m+1``) — attention masks everything
  beyond it (``kv_lens`` is data) and the next dispatch overwrites the
  stale slots, because writes route by ``block_table[slot, pos //
  page_size]``.  Published prefix-cache pages therefore only ever
  contain accepted tokens (publication is bounded by ``len_written``),
  and under ``kv_quant`` the accepted positions' bytes are identical to
  the non-speculative path (per-token absmax quantization is a pure
  function of each token's K/V vector).

Rejection sampling (``spec_rejection_sampling``, off by default) makes
speculative decoding lossless under a sampling temperature: draft
``d_i`` is accepted with probability ``p_i(d_i)`` (the proposers here
are deterministic, so the draft distribution is a delta and the
classic ``min(1, p/q)`` rule reduces to ``p``); a rejection resamples
from the residual ``p`` with ``d_i`` masked out, which preserves the
target distribution exactly.  Greedy acceptance under a temperature
WITHOUT rejection sampling skews the output distribution toward the
proposer — the PDT113 lint flags that construction.

Proposers:

* :class:`NGramProposer` — model-free prompt-lookup: match the tail of
  ``prompt + generated`` against earlier context and propose the
  tokens that followed the most recent earlier occurrence.  Zero extra
  FLOPs, zero state, fully CPU-testable; strongest on repetitive or
  quote-heavy text (and on greedy loops, which untrained models love).
* :class:`DraftModelProposer` — a small GPT/LLaMA drafts
  autoregressively against its OWN paged KV pool, run with the same
  page discipline as the engine (free-list allocator, reserved null
  page 0, per-request block tables).  Draft KV rolls back by longest
  common prefix with the committed stream, so rejected drafts cost
  exactly their stale positions (overwritten on the next propose).

The engine guards each verify dispatch per-draft: a slot whose segment
contains ANY non-finite row fails alone (``NonFiniteLogitsError``,
PDT-E018) while co-resident slots keep decoding — drilled by the
``engine_draft_nan`` fault site; ``engine_draft_mismatch`` corrupts a
slot's proposals to force rejection-path coverage (outputs stay
bitwise, only the accept rate moves).
"""
from __future__ import annotations

import numpy as np

__all__ = ["Proposer", "NGramProposer", "DraftModelProposer",
           "make_proposer", "accept_greedy", "accept_sampled"]


# ------------------------------------------------------------------ accept
def accept_greedy(drafts, greedy):
    """Longest-agreed-prefix acceptance: ``drafts`` [K] proposed tokens,
    ``greedy`` [K+1] the target's greedy pick after each segment
    position.  Returns the emitted tokens ``g_1..g_{m+1}`` (``m``
    leading matches plus the target's free next token) and ``m``."""
    drafts = np.asarray(drafts, np.int64).reshape(-1)
    greedy = np.asarray(greedy, np.int64).reshape(-1)
    m = 0
    while m < drafts.size and drafts[m] == greedy[m]:
        m += 1
    return greedy[:m + 1].astype(np.int32), m


def accept_sampled(drafts, logits, temperature, rng, *,
                   rejection_sampling=True):
    """Sampling-mode acceptance over the verify segment's logits rows.

    ``logits`` [K+1, V] float32, ``temperature`` > 0.  With
    ``rejection_sampling`` the deterministic-draft speculative-sampling
    rule runs: accept ``d_i`` with probability ``p_i(d_i)`` (the
    proposer's distribution is a delta at ``d_i``), on rejection
    resample from the residual ``p_i`` with ``d_i`` masked — the output
    distribution is exactly the target's.  Without it (the PDT113
    misconfiguration, kept only so the lint has a real semantic to
    describe) each row is sampled independently and drafts are accepted
    by token equality, which biases toward the proposer.  Returns
    ``(emitted tokens, accepted draft count)``."""
    drafts = np.asarray(drafts, np.int64).reshape(-1)
    lg = np.asarray(logits, np.float64) / max(float(temperature), 1e-6)
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    out = []
    if not rejection_sampling:
        sampled = np.array([rng.choice(p.shape[-1], p=row) for row in p])
        emitted, m = accept_greedy(drafts, sampled)
        return emitted, m
    m = 0
    for i, d in enumerate(drafts):
        if rng.random() < p[i, d]:
            out.append(int(d))
            m += 1
            continue
        resid = p[i].copy()
        resid[d] = 0.0
        z = resid.sum()
        if z <= 0.0:          # p was itself a delta at d: accept it
            out.append(int(d))
            m += 1
            continue
        out.append(int(rng.choice(resid.size, p=resid / z)))
        return np.asarray(out, np.int32), m
    # every draft accepted: the last row's sample is free
    out.append(int(rng.choice(p.shape[-1], p=p[drafts.size])))
    return np.asarray(out, np.int32), m


# --------------------------------------------------------------- proposers
class Proposer:
    """Interface the engine drives once per speculative decode step.

    ``propose(rid, ids, k)`` returns up to ``k`` int32 draft tokens
    predicted to continue ``ids`` (the request's committed
    ``prompt + generated`` stream, last element included).  Returning
    fewer — or none — is always legal: the engine falls back to a
    plain 1-token step for that slot.  ``bind(engine)`` runs once at
    engine construction (pool sizing); ``release(rid)`` whenever the
    engine's ``_release_slot`` funnel drops the request's pages
    (retire / finalize / preempt), so proposer state follows the
    engine's own page discipline."""

    def bind(self, engine):
        pass

    def propose(self, rid, ids, k):
        raise NotImplementedError

    def release(self, rid):
        pass


class NGramProposer(Proposer):
    """Model-free prompt-lookup proposer (n-gram suffix match).

    Matches the longest tail of length ``max_ngram`` down to
    ``min_ngram`` against EARLIER context; on a hit, proposes the
    tokens that followed the most recent earlier occurrence.  Costs no
    FLOPs and no state — the drafts are free, and wrong drafts cost
    nothing but their (rejected) verify rows."""

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = max(1, int(min_ngram))
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram < min_ngram")

    def propose(self, rid, ids, k):
        ids = np.asarray(ids, np.int32).reshape(-1)
        k = int(k)
        if k <= 0:
            return np.empty(0, np.int32)
        for n in range(min(self.max_ngram, ids.size - 1),
                       self.min_ngram - 1, -1):
            tail = ids[-n:]
            # windows ending strictly before the final position, newest
            # first: the most recent occurrence tracks local context
            win = np.lib.stride_tricks.sliding_window_view(
                ids[:-1], n)                       # [ids.size - n, n]
            hits = np.flatnonzero((win == tail).all(axis=1))
            if hits.size == 0:
                continue
            j = int(hits[-1])                      # latest occurrence
            cont = ids[j + n:j + n + k]
            if cont.size:
                return cont.astype(np.int32, copy=True)
        return np.empty(0, np.int32)


class _DraftSeq:
    __slots__ = ("pages", "ctx")

    def __init__(self):
        self.pages = []
        self.ctx = np.empty(0, np.int32)   # tokens whose KV is written


class DraftModelProposer(Proposer):
    """Draft-model proposer: a small causal LM generates K greedy draft
    tokens per request against its OWN paged KV pool.

    The pool runs the engine's page discipline — free-list allocator
    with reserved null page 0, per-request block tables sized to the
    engine's ``max_seq_len`` (``bind`` reads the geometry) — so draft
    KV scales with resident tokens and releases deterministically with
    the request.  Rejected drafts roll back by longest-common-prefix:
    the stale positions are simply re-written on the next propose
    (positional writes, same rollback argument as the target pool).

    Propose cost is one compiled single-token dispatch per token fed
    (catch-up + K drafts); the step program is cached on the draft
    model per geometry, so every request shares it."""

    def __init__(self, model, *, page_size=None, total_pages=None,
                 pages_per_block=None):
        model.eval()
        from ..models.generation import _decode_fn
        self.model = model
        self._decode, _, self._hard_limit = _decode_fn(model)
        self.page_size = page_size          # None: bind to the engine's
        self.total_pages = total_pages
        self.pages_per_block = pages_per_block
        self._caches = None
        self._free = None
        self._seqs: dict[object, _DraftSeq] = {}
        self._step_fn = None
        self.max_seq_len = None
        self.np_per_seq = None

    # pool construction is deferred to bind(): the proposer mirrors the
    # ENGINE's geometry (page size, sequence cap) so its free-list math
    # lines up with the requests it serves
    def bind(self, engine):
        from collections import deque

        from ..core.tensor import Tensor
        from ..models.generation import _zero_pool
        if self._caches is not None:
            return
        cfg = self.model.cfg
        self.page_size = int(self.page_size or engine.page_size)
        self.max_seq_len = int(engine.max_seq_len)
        if self._hard_limit and self.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"draft model max_seq_len {cfg.max_seq_len} < engine "
                f"max_seq_len {self.max_seq_len}: the draft cannot "
                f"reach every position the target serves")
        self.np_per_seq = -(-self.max_seq_len // self.page_size)
        if self.total_pages is None:
            self.total_pages = 1 + engine.max_slots * self.np_per_seq
        self.total_pages = int(self.total_pages)
        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        shape = (n_kv, self.total_pages, self.page_size, cfg.head_dim)
        self._caches = [Tensor(a) for a in _zero_pool(
            shape, 2 * cfg.num_layers)]
        self._free = deque(range(1, self.total_pages))  # 0 = null page

    def _get_step_fn(self):
        if self._step_fn is not None:
            return self._step_fn
        key = ("draft_step", self.page_size, self.np_per_seq,
               self.total_pages, self.pages_per_block)
        cache = self.model.__dict__.setdefault("_serving_step_cache", {})
        self._step_fn = cache.get(key)
        if self._step_fn is None:
            from .. import jit as jit_mod
            from ..models.generation import paged_slot_attention
            model, decode = self.model, self._decode
            ppb = self.pages_per_block

            def step(tok, pos, bt, *cs):
                import paddle_tpu as pp
                with pp.no_grad():
                    def attend(q, k, v, kc, vc, p):
                        return paged_slot_attention(
                            q, k, v, kc, vc, p, bt,
                            pages_per_block=ppb)
                    logits, new = decode(model, tok, pos, list(cs),
                                         attend=attend)
                return (logits,) + tuple(new)

            self._step_fn = jit_mod.to_static(step)
            cache[key] = self._step_fn
        return self._step_fn

    def _feed(self, tok, pos, bt):
        """One draft-model token: write KV at ``pos``, return greedy
        next token (host argmax — the draft is advisory, it needs no
        guard)."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        fn = self._get_step_fn()
        res = fn(Tensor(jnp.asarray([[tok]], jnp.int32)),
                 Tensor(jnp.asarray([pos], jnp.int32)),
                 Tensor(jnp.asarray(bt)), *self._caches)
        self._caches = list(res[1:])
        lg = np.asarray(res[0]._read()).astype(np.float32).reshape(-1)
        return int(lg.argmax())

    def propose(self, rid, ids, k):
        ids = np.asarray(ids, np.int32).reshape(-1)
        k = int(k)
        if self._caches is None:
            raise RuntimeError("DraftModelProposer.propose before "
                               "bind() — construct the engine first")
        if self._hard_limit:
            # learned position table: never feed past the draft's range
            k = min(k, self.model.cfg.max_seq_len - ids.size + 1)
        if k <= 0 or ids.size == 0:
            return np.empty(0, np.int32)
        st = self._seqs.setdefault(rid, _DraftSeq())
        # rollback: KV is valid exactly for the longest common prefix of
        # what was written and the committed stream
        n = min(st.ctx.size, ids.size - 1)
        lcp = 0
        if n:
            neq = np.flatnonzero(st.ctx[:n] != ids[:n])
            lcp = int(neq[0]) if neq.size else n
        need = -(-(ids.size + k - 1) // self.page_size)
        while len(st.pages) < need:
            if not self._free:
                return np.empty(0, np.int32)   # pool dry: no drafts
            st.pages.append(self._free.popleft())
        bt = np.zeros((1, self.np_per_seq), np.int32)
        bt[0, :len(st.pages)] = st.pages
        # catch-up (logits ignored) then K greedy drafts; every fed
        # token's KV lands at its position, so ctx records the stream
        out = []
        written = list(ids[:lcp])
        for pos in range(lcp, ids.size - 1):
            self._feed(int(ids[pos]), pos, bt)
            written.append(int(ids[pos]))
        tok = int(ids[-1])
        pos = ids.size - 1
        for _ in range(k):
            nxt = self._feed(tok, pos, bt)
            written.append(tok)
            out.append(nxt)
            tok, pos = nxt, pos + 1
        st.ctx = np.asarray(written, np.int32)
        return np.asarray(out, np.int32)

    def release(self, rid):
        st = self._seqs.pop(rid, None)
        if st is not None:
            self._free.extend(st.pages)

    @property
    def pages_free(self):
        """Free-list depth (tests audit the draft pool's conservation
        the same way they audit the engine's)."""
        return len(self._free) if self._free is not None else None


def make_proposer(spec):
    """Resolve the engine's ``spec_proposer`` kwarg / flag: a
    :class:`Proposer` instance passes through, ``"ngram"`` builds the
    model-free default.  (A draft model has constructor knobs of its
    own — pass a :class:`DraftModelProposer` instance.)"""
    if isinstance(spec, Proposer):
        return spec
    if isinstance(spec, str) and spec.lower() in ("ngram",
                                                  "prompt_lookup"):
        return NGramProposer()
    raise ValueError(
        f"spec_proposer={spec!r}: expected a Proposer instance or "
        f"'ngram'")

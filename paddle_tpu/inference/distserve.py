"""Disaggregated prefill/decode serving over TP-sharded engine workers
(ISSUE 13; ROADMAP item #2 — the last single-chip wall).

Production serving splits COMPUTE-bound prefill from LATENCY-bound
decode (TPLA, PAPERS.md #4): a prefill burst that lands on a colocated
engine steals whole mixed-program dispatches from every resident
decode, so decode p99 tracks arrival bursts instead of the hardware.
Here the two phases run as SEPARATE worker groups of
:class:`~paddle_tpu.inference.ContinuousBatchingEngine` instances —
each group optionally TP-sharded over its own mesh (``mesh=`` /
``tp_axis=`` engine kwargs; ``models/generation.py`` TP section) —
with a KV-PAGE HANDOFF between them:

* admission prefills on the prefill group (chunked, ragged-batched —
  the engine's normal mixed program, ``max_new_tokens=1`` so the slot
  stops right after its first token);
* the moment a request's first token exists, its live pages (+ int8
  scale side-pools), block-table row and scheduler state serialize
  into a :class:`KVPageTransport` payload — ONLY the request's written
  pages move, nothing pool-shaped — and ship under bounded
  ``resilience.retry`` (``engine_handoff_transient`` drills the
  transient path);
* the decode group imports the payload: page ids remap into its own
  free list, bytes scatter in one compiled dispatch, and the request
  continues through the UNTOUCHED decode-window / speculative paths.
  Prefix-cache publish happens on the decode side (retire publishes
  the decode engine's pages, and ``import_request`` retains pages the
  decode cache already indexes for the same prefix), so cached
  prefixes survive handoff; the prefill side keeps its own cache for
  cross-request prompt reuse before the handoff.

Because the ragged kernel treats block tables and lengths as pure
data, the handoff is a byte copy plus a table rewrite — no recompiles,
and the decode stream is BITWISE the colocated engine's (greedy
decode is deterministic and KV bytes are a pure function of the token
prefix; ``tests/test_distserve.py`` pins colocated-vs-disagg token
equality with pool conservation on both groups).

Failure model: a handoff transient retries bounded
(``serving_disagg_handoff_retries``); a lost decode worker
(``engine_decode_worker_lost`` drill) discards the payload and
REQUEUES the request to the prefill group for a from-scratch
re-prefill — bitwise, only the ``requeues`` counter moves.

Observability: every handoff runs under a ``serving.handoff`` tracing
span, emits a ``serving.handoff`` event (rid/bytes/ms) into the ring,
and feeds the coordinator registry's ``serving.handoff_ms`` histogram
and ``serving.handoff_bytes``/``serving.handoffs``/``serving.requeues``
counters — serving_bench's ``disagg`` row reads them.
"""
from __future__ import annotations

import pickle
import time
from collections import deque

import numpy as np

from ..core.state import get_flag as _get_flag
from ..observability import Registry as _ObsRegistry
from ..observability import events as _events
from ..observability import tracing as _tracing
from ..observability import watchdog as _watchdog
from ..observability.metrics import LATENCY_BUCKETS_MS
from ..resilience import faults
from ..resilience.retry import retry_call
from ..resilience.serving import (SITE_DECODE_WORKER_LOST,
                                  SITE_HANDOFF_TRANSIENT,
                                  SITE_MIGRATION_TRANSIENT)
from .engine import CompletedRequest, ContinuousBatchingEngine

__all__ = ["DisaggServer", "KVPageTransport", "register_decode_worker",
           "rpc_deliver_payload", "rpc_restore_payload"]


# ------------------------------------------------------------------ rpc
# decode workers reachable over distributed/rpc register their engine
# here (process-global, like the rpc agent itself); the transport ships
# pickled payload bytes to ``rpc_deliver_payload`` on the worker
_DECODE_WORKERS: dict = {}


def register_decode_worker(name: str, engine) -> None:
    """Expose ``engine`` to rpc handoffs under ``name`` (call on the
    decode worker process after ``rpc.init_rpc``)."""
    _DECODE_WORKERS[str(name)] = engine


def rpc_deliver_payload(name: str, data: bytes, max_new_tokens: int,
                        deadline_ms=None):
    """Server-side half of an rpc handoff: deserialize and import into
    the registered decode engine.  Returns the imported rid, or None
    when the worker has no capacity right now (the caller retries)."""
    eng = _DECODE_WORKERS.get(str(name))
    if eng is None:
        raise KeyError(f"no decode worker registered as {name!r}")
    return eng.import_request(pickle.loads(data), max_new_tokens,
                              deadline_ms=deadline_ms)


def rpc_restore_payload(name: str, data: bytes):
    """Server-side half of an rpc live migration (ISSUE 20):
    deserialize a ``snapshot_request`` payload and restore it into the
    registered engine.  Returns the restored rid, or None when the
    engine has no capacity right now (the caller retries)."""
    eng = _DECODE_WORKERS.get(str(name))
    if eng is None:
        raise KeyError(f"no worker registered as {name!r}")
    return eng.restore_request(pickle.loads(data))


class KVPageTransport:
    """Serialize + ship one request's live KV pages between engines.

    The payload (``engine.export_request``) pickles to bytes even for
    the in-process path, so every handoff exercises the real wire
    encoding; ``to=`` names an rpc worker (``distributed/rpc``) that
    registered its engine via :func:`register_decode_worker`, in which
    case the bytes cross the socket.  ``ship`` runs under bounded
    ``resilience.retry`` on transient ``ConnectionError`` — the
    ``engine_handoff_transient`` fault site drills exactly that.
    """

    def __init__(self, to=None, retries=None):
        self.to = to
        self.retries = int(_get_flag("serving_disagg_handoff_retries")
                           if retries is None else retries)

    def ship(self, payload, dst_engine, max_new_tokens,
             deadline_ms=None, on_retry=None):
        """Move ``payload`` into ``dst_engine`` (or the rpc worker when
        ``to`` is set).  Returns ``(rid_or_None, n_bytes)`` — None when
        the destination has no capacity yet (retry after a step)."""
        rid = payload["rid"]
        data = pickle.dumps(payload)

        def _send():
            faults.maybe_raise(SITE_HANDOFF_TRANSIENT, str(rid))
            if self.to is not None:
                from ..distributed.rpc import rpc_sync
                return rpc_sync(self.to, rpc_deliver_payload,
                                args=(self.to, data, max_new_tokens,
                                      deadline_ms))
            return dst_engine.import_request(
                pickle.loads(data), max_new_tokens,
                deadline_ms=deadline_ms)

        out = retry_call(_send, max_attempts=max(1, self.retries + 1),
                         base_delay=0.005, max_delay=0.05,
                         retry_on=(ConnectionError,),
                         on_retry=on_retry)
        return out, len(data)

    def ship_snapshot(self, payload, dst_engine, on_retry=None):
        """Live-migration half (ISSUE 20): move a full-request
        ``snapshot_request`` payload into ``dst_engine.restore_request``
        (or the rpc worker when ``to`` is set) under the same bounded
        retry discipline — the ``router_migration_transient`` fault
        site sits INSIDE the retried closure, so a ``*N`` drill is
        absorbed by N retries exactly like a real transient.  A torn
        payload surfaces ``MigrationError`` (PDT-E025) from the
        restore CRC check UNRETRIED (it is not a ConnectionError): the
        source keeps the request.  Returns ``(rid_or_None, n_bytes)``
        — None when the destination has no capacity yet."""
        rid = payload["rid"]
        data = pickle.dumps(payload)

        def _send():
            faults.maybe_raise(SITE_MIGRATION_TRANSIENT, str(rid))
            if self.to is not None:
                from ..distributed.rpc import rpc_sync
                return rpc_sync(self.to, rpc_restore_payload,
                                args=(self.to, data))
            return dst_engine.restore_request(pickle.loads(data))

        out = retry_call(_send, max_attempts=max(1, self.retries + 1),
                         base_delay=0.005, max_delay=0.05,
                         retry_on=(ConnectionError,),
                         on_retry=on_retry)
        return out, len(data)


class _DisaggReq:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos", "deadline",
                 "state", "requeues")

    def __init__(self, rid, prompt, max_new_tokens, eos, deadline):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos = eos
        self.deadline = deadline  # ABSOLUTE clock seconds | None: armed
        # once at coordinator admission, so the prefill engine, the
        # handoff wait and the decode engine all spend from ONE budget
        # (a per-engine re-arm would let a request run ~2x its TTL)
        self.state = "pending"   # pending|prefill|ready|decode|done
        self.requeues = 0


class DisaggServer:
    """Coordinator for disaggregated prefill/decode serving.

    ``prefill_workers``/``decode_workers`` engine instances are built
    from the shared ``model`` plus per-group kwargs
    (``prefill_kwargs``/``decode_kwargs`` — pool geometry, mesh=/
    tp_axis= TP sharding, kv_quant ...; both groups must agree on
    ``page_size`` and ``kv_quant``, the KV wire layout).  The API
    mirrors the engine: :meth:`add_request`, :meth:`step` (returns
    completed requests), :meth:`run` to drain, ``stats`` /
    :meth:`metrics`.

    A request's life: pending -> prefill group (``max_new_tokens=1``)
    -> first token -> export + :class:`KVPageTransport` handoff ->
    decode group import -> decode windows -> completion surfaces from
    :meth:`step`.  An eos at the first token completes on the prefill
    side without a handoff; prefill-side failures/timeouts surface as
    final results.  ``engine_decode_worker_lost`` requeues to the
    prefill group (bitwise re-prefill).

    Both groups inherit the engine's compile-time program audit: every
    cached program (import scatter, decode windows, TP wrappers) runs
    through the whole-program jaxpr analyzer once per geometry at
    first compile (``analysis/program.py``; ``PDTPU_ANALYSIS``-gated).
    """

    def __init__(self, model, *, prefill_workers=None,
                 decode_workers=None, transport=None,
                 prefill_kwargs=None, decode_kwargs=None, clock=None):
        npf = int(_get_flag("serving_disagg_prefill_workers")
                  if prefill_workers is None else prefill_workers)
        ndc = int(_get_flag("serving_disagg_decode_workers")
                  if decode_workers is None else decode_workers)
        if npf < 1 or ndc < 1:
            raise ValueError("DisaggServer needs >= 1 prefill and >= 1 "
                             "decode worker")
        pk = dict(prefill_kwargs or {})
        dk = dict(decode_kwargs or {})
        if clock is not None:
            pk.setdefault("clock", clock)
            dk.setdefault("clock", clock)
        self.prefill_group = [ContinuousBatchingEngine(model, **pk)
                              for _ in range(npf)]
        self.decode_group = [ContinuousBatchingEngine(model, **dk)
                             for _ in range(ndc)]
        p0, d0 = self.prefill_group[0], self.decode_group[0]
        if (p0.page_size != d0.page_size
                or p0.kv_quant != d0.kv_quant):
            raise ValueError(
                "prefill and decode groups must share page_size and "
                "kv_quant — they are the KV handoff wire layout")
        self.transport = transport or KVPageTransport()
        self._clock = time.monotonic if clock is None else clock
        self._reqs: dict = {}            # rid -> _DisaggReq
        self._pending: deque = deque()   # rids awaiting prefill entry
        self._ready: deque = deque()     # (rid, payload) awaiting import
        self._finalized: list = []       # coordinator-side completions
        # (timeouts of parked requests) surfaced by the NEXT step() —
        # exception-safe: a handoff error later in the same tick
        # cannot lose them
        self._next_rid = 0
        self._rr = 0                     # decode-group round robin
        self._step_n = 0
        self._done_at: dict = {}         # rid -> step_n when finalized
        self._registry = _ObsRegistry("serving_disagg")
        reg = self._registry
        self._c_handoffs = reg.counter("serving.handoffs", always=True)
        self._c_bytes = reg.counter("serving.handoff_bytes",
                                    always=True)
        self._c_requeues = reg.counter("serving.requeues", always=True)
        self._c_retries = reg.counter("serving.handoff_retries",
                                      always=True)
        self._h_handoff = reg.histogram(
            "serving.handoff_ms", "export -> decode-import wall time",
            LATENCY_BUCKETS_MS)

    # ------------------------------------------------------------ API --
    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    request_id=None, deadline_ms=None, requeue=False):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # eager validation against the DECODE group's budget — the
        # group that must hold the full sequence.  The prefill group
        # only ever sees prompt+1 tokens, so without this check an
        # oversized request would admit cleanly and then crash
        # import_request mid-handoff (the engine's own add_request
        # rejects these at admission for exactly this reason).
        dec = self.decode_group[0]
        total = prompt.size + int(max_new_tokens)
        if total > dec.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens > decode-group "
                f"max_seq_len {dec.max_seq_len}")
        need_full = -(-total // dec.page_size)
        if need_full > dec.total_pages - 1:
            from ..core.errors import PageBudgetError
            raise PageBudgetError(
                f"request needs {need_full} pages but the decode "
                f"pool only has {dec.total_pages - 1} "
                f"[{PageBudgetError.error_code}]")
        pre = self.prefill_group[0]
        if prompt.size + 1 > pre.max_seq_len:
            raise ValueError(
                f"prompt needs {prompt.size + 1} tokens > "
                f"prefill-group max_seq_len {pre.max_seq_len}")
        need_pf = -(-(prompt.size + 1) // pre.page_size)
        if need_pf > pre.total_pages - 1:
            from ..core.errors import PageBudgetError
            raise PageBudgetError(
                f"prompt needs {need_pf} pages but the prefill pool "
                f"only has {pre.total_pages - 1} "
                f"[{PageBudgetError.error_code}]")
        if request_id is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = request_id
            if isinstance(rid, int):
                self._next_rid = max(self._next_rid, rid + 1)
            if rid in self._reqs and self._reqs[rid].state != "done":
                raise ValueError(f"request_id {rid!r} already in flight")
        deadline = (self._clock() + float(deadline_ms) / 1e3) \
            if deadline_ms else None
        r = _DisaggReq(rid, prompt, max_new_tokens,
                       eos_token_id, deadline)
        if requeue:
            # a fleet-router requeue: the request's prefill demand was
            # already counted on its first admission — mark it so
            # _submit_pending's engine admission skips the re-count
            r.requeues = 1
        self._reqs[rid] = r
        self._pending.append(rid)
        return rid

    def _remaining_ms(self, r):
        """Milliseconds left on ``r``'s coordinator-armed deadline
        (None = no deadline).  Engines get the REMAINING budget, never
        a fresh one."""
        if r.deadline is None:
            return None
        return (r.deadline - self._clock()) * 1e3

    @property
    def has_work(self):
        return bool(self._pending) or bool(self._ready) \
            or bool(self._finalized) or any(
                e.has_work for e in self.prefill_group
                + self.decode_group)

    def cached_prefix_tokens(self, ids) -> int:
        """Fleet-router affinity query: the longest page-aligned
        prefix of ``ids`` any PREFILL engine already holds (re-prefill
        lands on the prefill group, so that is where a routed prompt's
        cached pages pay off)."""
        return max(e.cached_prefix_tokens(ids)
                   for e in self.prefill_group)

    def pending_requests(self):
        """Request ids still in flight across the coordinator and both
        groups — the fleet router's live-load gauge."""
        return [rid for rid, r in self._reqs.items()
                if r.state != "done"]

    @property
    def stats(self):
        """Coordinator counters plus per-group aggregates."""
        d = {
            "handoffs": self._c_handoffs.value,
            "handoff_bytes": self._c_bytes.value,
            "handoff_retries": self._c_retries.value,
            "requeues": self._c_requeues.value,
            "pending": len(self._pending),
            "ready": len(self._ready),
        }
        for name, group in (("prefill", self.prefill_group),
                            ("decode", self.decode_group)):
            st = [e.stats for e in group]
            d[f"{name}_admitted"] = sum(s["admitted"] for s in st)
            d[f"{name}_tokens_generated"] = sum(
                s["tokens_generated"] for s in st)
            d[f"{name}_pages_in_use"] = sum(
                s["pages_in_use"] for s in st)
        return d

    def metrics(self) -> dict:
        """The coordinator registry snapshot (handoff histograms and
        counters).  Per-request serving timelines live on the group
        engines — ``server.decode_group[0].metrics()`` has the decode
        TTFT/TPOT story."""
        return self._registry.snapshot()

    def slo_status(self) -> dict:
        """Per-group SLO status (ISSUE 14): every worker engine's
        :meth:`ContinuousBatchingEngine.slo_status` list, keyed
        ``prefill``/``decode``.  Specs arm through the per-group
        engine kwargs (``prefill_kwargs``/``decode_kwargs`` ``slo=``)
        or the ``serving_slo`` flag — disaggregation exists to protect
        decode TPOT tails, so the decode group is where the TPOT
        objective normally lives."""
        return {
            "prefill": [s for e in self.prefill_group
                        for s in e.slo_status()],
            "decode": [s for e in self.decode_group
                       for s in e.slo_status()],
        }

    def step(self):
        """One coordinator tick: feed pending admissions to the
        prefill group, step it, export + hand off first-token slots,
        import ready payloads into the decode group, step it.  Returns
        the requests completed this tick (decode completions plus
        prefill-side finals: first-token eos, failures, timeouts, and
        coordinator-side deadline expiries)."""
        self._step_n += 1
        out = list(self._finalized)      # survivors of a prior tick's
        self._finalized.clear()          # mid-loop exception included
        self._submit_pending()
        for eng in self.prefill_group:
            for c in eng.step():
                done = self._on_prefill_complete(c)
                if done is not None:
                    out.append(done)
            self._export_first_tokens(eng)
        self._deliver_ready()
        out.extend(self._finalized)
        self._finalized.clear()
        for eng in self.decode_group:
            for c in eng.step():
                r = self._reqs.get(c.request_id)
                if r is not None:
                    self._mark_done(r)
                out.append(c)
        # prune bookkeeping for requests finalized a few ticks ago:
        # the entry is only needed to swallow the prefill engine's
        # one-tick-late 'length' echo, and a long-running coordinator
        # must not retain every dead request's prompt forever
        for rid, n in list(self._done_at.items()):
            if n <= self._step_n - 3:
                del self._done_at[rid]
                self._reqs.pop(rid, None)
        return out

    def _mark_done(self, r):
        r.state = "done"
        self._done_at[r.rid] = self._step_n

    def _timeout(self, r, tokens=()):
        """Finalize ``r`` at the coordinator (deadline expired while
        pending or parked in the handoff queue — windows no engine
        sweep covers).  Goes through ``_finalized`` so a handoff
        exception later in the same tick cannot lose the result."""
        self._mark_done(r)
        self._finalized.append(CompletedRequest(
            r.rid, r.prompt, np.asarray(list(tokens), np.int32),
            "timeout"))

    def run(self, max_steps=10000):
        """Drain: step until every request completes.  Returns
        {request_id: CompletedRequest} in completion order."""
        import warnings
        done = {}
        for _ in range(max_steps):
            if not self.has_work:
                break
            for c in self.step():
                done[c.request_id] = c
        if self.has_work:
            warnings.warn(
                f"DisaggServer.run: step budget ({max_steps}) "
                f"exhausted with requests still in flight",
                RuntimeWarning, stacklevel=2)
        return done

    # ----------------------------------------------------- internals --
    def _submit_pending(self):
        kept = deque()
        # the in-flight guard must union EVERY prefill engine: after a
        # worker-lost requeue the old slot may still be draining on a
        # different engine than the one the balancer would pick, and a
        # double admission would surface a truncated duplicate result
        in_flight = set()
        for e in self.prefill_group:
            in_flight |= {q.rid for q in e._queue} | {
                s.req.rid for s in e._slots if s.req is not None}
        try:
            while self._pending:
                rid = self._pending.popleft()
                r = self._reqs[rid]
                if r.state == "done":
                    continue   # finalized elsewhere (engine-side
                               # timeout of the old slot): drop
                rem = self._remaining_ms(r)
                if rem is not None and rem <= 0:
                    self._timeout(r)
                    continue
                if rid in in_flight:  # old slot still draining after
                    kept.append(rid)  # a worker-lost requeue: wait
                    continue
                eng = min(self.prefill_group,
                          key=lambda e: len(e._queue))
                try:
                    # prefill side generates exactly the FIRST token;
                    # the real budget rides the payload to decode.
                    # requeues (worker-lost, router requeue) re-admit
                    # a request whose demand is already counted —
                    # requeue=True keeps prefill_tokens_requested a
                    # once-per-request demand figure while computed
                    # meters the actual (net-of-cache) recompute
                    eng.add_request(r.prompt, 1, eos_token_id=r.eos,
                                    request_id=rid, deadline_ms=rem,
                                    requeue=r.requeues > 0)
                except Exception:
                    kept.append(rid)      # keep: retry next tick
                    raise
                r.state = "prefill"
        finally:
            # exception-safe: whatever this tick did not reach stays
            # queued instead of vanishing mid-loop
            kept.extend(self._pending)
            self._pending = kept

    def _export_first_tokens(self, eng):
        """Export every prefill slot that just produced its first
        token (phase flipped to decode); the slot retires on the
        engine's next step and its pages publish into the PREFILL
        side's prefix cache — export is a copy, not a steal."""
        for s in eng._slots:
            if s.req is None or s.phase != "decode":
                continue
            r = self._reqs.get(s.req.rid)
            if r is None or r.state != "prefill":
                continue
            t0 = int(s.out_toks[-1]) if s.out_toks else None
            if t0 is not None and r.eos is not None \
                    and t0 == int(r.eos):
                # eos at the first token: complete on the prefill side
                # (the engine's own retire will emit reason "stop" —
                # _on_prefill_complete surfaces it)
                r.state = "eos_at_first"
                continue
            if r.max_new_tokens <= len(s.out_toks):
                # budget exhausted by the first token (max_new=1):
                # the prefill result IS the final result — no handoff;
                # the engine retires it "length" and, with r.state
                # still "prefill", _on_prefill_complete surfaces it
                continue
            payload = eng.export_request(r.rid)
            r.state = "ready"
            self._ready.append((r.rid, payload))

    def _on_prefill_complete(self, c):
        """A prefill engine retired ``c``.  Handed-off requests retire
        with reason 'length' after their single budgeted token — that
        is the expected lifecycle event, swallowed here; the same echo
        arrives one tick late for a request the coordinator already
        requeued ('pending', worker-lost) or finalized ('done',
        parked-timeout), and must ALSO be swallowed or step() would
        surface a spurious truncated duplicate.  Everything else
        (first-token eos, single-token-budget 'length', failures,
        engine-side timeouts of an active prefill) is final."""
        r = self._reqs.get(c.request_id)
        if r is None:
            return c
        if r.state == "done":
            return None        # coordinator already finalized this rid
        if c.finish_reason == "length" and r.state in ("ready",
                                                       "decode",
                                                       "pending"):
            return None        # handoff in flight / requeue draining
        self._mark_done(r)
        return c

    def _deliver_ready(self):
        kept = deque()
        try:
            self._deliver_ready_inner(kept)
        finally:
            # exception-safe: a ship() that exhausts its retries must
            # not strand the payloads already parked in ``kept`` (nor
            # the ones still unprocessed) — recombine before the error
            # propagates so a caller that keeps stepping retries them
            kept.extend(self._ready)
            self._ready = kept

    def _deliver_ready_inner(self, kept):
        while self._ready:
            rid, payload = self._ready.popleft()
            r = self._reqs[rid]
            if r.state == "done":
                continue       # finalized elsewhere: drop the payload
            rem = self._remaining_ms(r)
            if rem is not None and rem <= 0:
                # expired while parked in the handoff queue — a window
                # neither engine's sweep covers
                self._timeout(r, payload["done_toks"])
                continue
            if faults.check(SITE_DECODE_WORKER_LOST, key=str(rid)):
                # decode worker died before the ack: the payload is
                # gone with it — requeue for a from-scratch re-prefill
                # (bitwise: greedy prefill+decode is deterministic)
                self._c_requeues.inc()
                r.state = "pending"
                r.requeues += 1
                self._pending.append(rid)
                _events.emit("serving.handoff_worker_lost", rid=rid)
                continue
            eng = self.decode_group[self._rr % len(self.decode_group)]
            self._rr += 1
            if self.transport.to is None and not any(
                    s.req is None for s in eng._slots):
                # no free slot on the (local) target: don't serialize
                # a multi-page payload just to have import refuse it —
                # park and retry next tick (import_request still
                # re-checks, covering page pressure; an rpc worker's
                # capacity is only knowable by asking, so that path
                # ships regardless)
                kept.append((rid, payload))
                continue

            def _on_retry(_exc, _n):
                self._c_retries.inc()

            t0 = time.perf_counter()
            with _tracing.span("serving.handoff", rid=str(rid),
                               pages=int(payload["n_pages"])):
                # stall watchdog (ISSUE 14): a wedged transfer past
                # the deadline gets thread stacks + a flight record
                # (no interrupt — the payload stays parked and the
                # next tick retries the handoff)
                wd = _watchdog.arm(
                    "serving.handoff",
                    float(_get_flag("watchdog_stall_ms")),
                    key=str(rid))
                try:
                    got, nbytes = self.transport.ship(
                        payload, eng, r.max_new_tokens,
                        deadline_ms=rem, on_retry=_on_retry)
                except Exception:
                    # retries exhausted (or a non-transient transport
                    # error): keep the payload so the next step()
                    # retries the handoff instead of stranding the rid
                    kept.append((rid, payload))
                    raise
                finally:
                    wd.disarm()
                ms = (time.perf_counter() - t0) * 1e3
                if got is None:
                    kept.append((rid, payload))   # no capacity yet
                    continue
                r.state = "decode"
                self._c_handoffs.inc()
                self._c_bytes.inc(nbytes)
                self._h_handoff.observe(ms)
                _events.emit("serving.handoff", rid=rid,
                             bytes=int(nbytes), ms=round(ms, 3),
                             pages=int(payload["n_pages"]))

"""Continuous-batching serving engine over paged KV caches.

Capability analog of the request-level scheduling the reference's
``block_multi_head_attention`` kernel exists to serve
(``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``;
python surface ``incubate/nn/functional/block_multihead_attention.py``)
— the piece VERDICT r5 named as missing ("no request-level scheduler
that admits/retires sequences mid-decode").  Design follows the
Gemma-on-TPU serving study (arxiv 2605.25645, PAPERS.md): TPU serving
throughput comes from continuous batching over fixed-shape buckets.

Shape discipline (TPU-native):

* ONE page pool per layer ``[Hkv, total_pages, page_size, D]``; a
  free-list allocator hands pages to admitted requests and takes them
  back at retirement — HBM scales with resident tokens, not with
  ``max_slots * max_len``.  Page 0 is the reserved NULL page: inactive
  slots and packing padding write there, so retired block-table rows
  can never scribble a reassigned page.
* TWO compiled programs total, both bucket-stable:
  - the MIXED step (token budget T): prefill chunks of admitted
    requests packed together with one token from every ongoing decode —
    ``models.generation.ragged_paged_step`` serves both through one
    ragged kernel call.  Admission never stalls ongoing decodes, and a
    prompt longer than the budget prefills across consecutive steps
    (chunked prefill);
  - the DECODE window: ``decode_window`` steps scanned into one
    dispatch, slot state (tokens, positions, finished mask, page
    tables, KV pools) carried through the scan — one host round-trip
    per K tokens.
  Admission, retirement, preemption, cancellation and deadlines only
  change tensor VALUES (block tables, lengths, masks, the guard's
  poison vector) between dispatches — shapes never change, so no
  per-request recompiles.
* Greedy decoding (the serving bench's measurement mode); sampling
  belongs to ``models.generate``.

Overload behavior (ISSUE 5; the Gemma study and the Ragged Paged
Attention paper both treat admission under bounded HBM and
eviction/recompute of preempted sequences as first-class serving
mechanics):

* ON-DEMAND paging — admission reserves pages for the prompt plus one
  decode page only; block tables grow as decode crosses page
  boundaries.  Under pool pressure the allocator PREEMPTS a victim
  slot (latest-admitted first, never one admitted before the grower),
  returns its pages and requeues it at the queue head; re-admission
  re-prefills ``prompt + tokens_so_far``, which is bitwise-identical
  to an uncontended run (greedy decode is deterministic and the
  ragged prefill and decode paths agree bitwise — the engine-vs-
  generate parity tests pin that).  The earliest-admitted resident can
  always grow (eager admission bounds every request by the pool), so
  overload degrades throughput, never liveness.
* ADMISSION CONTROL — ``max_queue`` bounds the queue; policy
  ``reject`` raises :class:`~paddle_tpu.core.errors.QueueFullError`
  (PDT-E017), ``block`` steps the engine until room frees.  Requests
  that can NEVER fit the pool are rejected eagerly at ``add_request``
  with :class:`~paddle_tpu.core.errors.PageBudgetError` (PDT-E016).
* DEADLINES / CANCELLATION — per-request ``deadline_ms`` checked at
  step boundaries (``finish_reason == "timeout"``), ``cancel(rid)``
  for queued or resident requests (``"cancelled"``).
* DECODE GUARD — a device-side finite-ness flag over each slot's
  logits rides the mixed program and the decode-window scan carry
  (``models.generation.guarded_argmax``); a non-finite request fails
  ALONE (``finish_reason == "failed"``, coded
  ``NonFiniteLogitsError`` recorded on the result) while co-resident
  requests finish unperturbed.
* FAULT DRILLS — every dispatch runs under bounded
  ``resilience.retry``; the ``engine_dispatch`` / ``engine_nan_decode``
  / ``engine_page_pressure`` / ``engine_cache_evict`` sites
  (``resilience.serving``) drill the retry, guard, preemption and
  eviction paths deterministically.

Prefix caching (ISSUE 6; ``inference/prefix_cache.py``):

* CROSS-REQUEST KV REUSE — retirement and preemption PUBLISH a
  request's fully-written pages into a radix index keyed on
  page-granular token content instead of freeing them; admission walks
  the index and maps the matched prefix onto the existing pages
  (per-page refcounts pin shared pages while any resident uses them),
  so prefill starts at the first uncached token.  Because the ragged
  kernel treats block tables and lengths as data, a cache hit is
  purely a block-table indirection — outputs are bitwise-identical to
  the uncached engine and to ``generate(kv_cache='paged')``.
* COPY-ON-WRITE at the divergence page — a fully-cached (page-aligned)
  prompt still needs its last position's logits, so the last matched
  page is device-COPIED (one donated dispatch) and the one recomputed
  token writes to the private copy; every other admission starts
  prefill at a page boundary past the match, so shared pages are never
  write targets.
* LRU EVICTION — ref-0 cached pages are reclaimed least-recently-used
  (trie leaves first) before the allocator resorts to preemption;
  an evicted prefix transparently re-prefills.  Preempt-requeue
  re-admission hits the victim's own just-published pages, fixing the
  recompute gap: only ``tokens_since_last_full_page`` are re-prefilled
  instead of ``prompt + tokens_so_far``.
* The ``serving_prefix_cache`` flag (default on; ``off`` restores the
  uncached engine bitwise) / ``prefix_cache`` engine kwarg gate it;
  ``stats`` grows ``cache_hits`` / ``cache_hit_tokens`` /
  ``cached_pages`` / ``evictions`` and the prefill accounting pair
  ``prefill_tokens_requested`` / ``prefill_tokens_computed``.

Quantized KV (ISSUE 7; ``serving_kv_quant`` flag / ``kv_quant`` kwarg,
default off):

* INT8 PAGE POOLS — data pools store int8 and per-page scale
  side-pools ([Hk, P, page_size] f32, ``quantization.kv_quantize``)
  APPEND to the cache list; writes quantize inside
  ``models.generation.ragged_paged_step`` / ``paged_slot_attention``
  (each token's bytes a pure function of its own K/V vector — page
  content is write-path-independent), reads dequantize inside the
  ragged kernel's DMA loop.  KV bytes per resident sequence drop to
  ``(D + 4) / 4D`` of fp32 (< 0.5 for every real head dim;
  ``stats["kv_page_bytes"]``), which halves the HBM roofline term and
  doubles the sequences a fixed pool can hold.
* Because the scale pools ride the SAME block tables and page ids, the
  prefix cache (match, COW, publish, eviction), preempt-requeue and
  the decode-window donation all carry them transparently — no scale-
  aware branch exists anywhere in the scheduling layer.
* Greedy outputs are token-identical to the fp engine on the serving
  parity suite (int8 absmax per-vector error does not flip tiny-model
  argmax); with the flag off the engine is bitwise-identical to the
  pre-quantization fp path.

Observability (ISSUE 8; ``paddle_tpu.observability``):

* The engine's counters are RE-BACKED by a private metrics registry —
  ``stats`` keeps its exact pre-existing keys/values (always-on
  counters; the ``PDTPU_METRICS`` flag cannot zero the contract) while
  ``metrics()`` returns the full snapshot: the counters plus derived
  per-request timelines (queue-time, TTFT, TPOT,
  decode-tokens-per-window and per-dispatch latency histograms,
  finish-reason-labeled counters).  Phase attribution NEEDS engine
  events: prefill chunks and decodes share one ragged dispatch, so
  wrapping calls with host timers cannot tell requests apart.
* Scheduling emits structured events (enqueued / admitted /
  prefill_chunk / first_token / decode_window / preempted / retired,
  plus dispatch kinds) into the process event ring; coded failures —
  the decode guard's ``NonFiniteLogitsError``, a
  ``CacheIntegrityError`` page-conservation violation, the pool
  backstop — dump the ring as a JSON flight record
  (``PDTPU_FLIGHT_DIR``), so the postmortem starts from the last N
  events.  Clean runs dump nothing; ``PDTPU_METRICS=off`` restores
  the pre-observability engine bitwise (serving_bench's
  ``metrics_overhead`` row pins the on state at <= 3% tokens/sec).
* SLO GUARDRAILS & STALL WATCHDOG (ISSUE 14) — ``slo=`` arms
  declarative objectives (``observability/slo.py``) over the engine's
  own timeline histograms, evaluated at step boundaries over sliding
  windows with multi-window burn-rate alerting (``slo_status()``;
  breach -> ``slo.breach`` event + flight dump; budget gauges in
  ``render_prometheus()``); ``watchdog_ms=`` arms every dispatch with
  a stall deadline (``observability/watchdog.py``) past which thread
  stacks + the flight record + a Chrome trace are captured and a
  coded ``EngineStallError`` (PDT-E020) surfaces from ``step()``
  instead of a hang — drilled by the ``engine_stall`` fault site.
  Both are metrics-flag-gated no-ops when off.
* DISTRIBUTED TRACING (ISSUE 12; ``observability/tracing.py``) — every
  dispatch runs under a ``serving.dispatch`` span whose begin/end pair
  lands in the event ring, and the timeline's ``serving.dispatch``
  event carries the active ``trace_id``/``parent_id`` — a trace
  propagated in over ``distributed/rpc`` (disaggregated
  prefill/decode handoff) threads through to the dispatches that
  served it.  ``observability.export_trace(path)`` renders the ring
  (lifecycle events per slot, dispatch spans, faults) as a Perfetto
  trace, one track per engine slot.

Speculative decoding (ISSUE 9; ``inference/speculative.py``,
``spec_decode`` kwarg / ``serving_spec_*`` flags, default off):

* DRAFT-PROPOSE / RAGGED-VERIFY — per decode step each slot submits
  its current token plus up to K proposed tokens as ONE ragged
  segment (``q_lens = K+1``) through the mixed program; the verify
  entry (``models.generation.verify_argmax``) returns the target's
  greedy pick after EVERY position, and the slot advances by the
  longest agreed draft prefix plus the target's free next token —
  1..K+1 tokens per dispatch instead of exactly one.  Greedy outputs
  are BITWISE-identical to ``spec_decode=off``: accepted tokens are by
  construction the tokens plain decode would have produced.
* RAGGED RETIREMENT / KV ROLLBACK — each slot's ``cur_pos`` /
  ``len_written`` advances by its own accept count; KV written past
  the first rejection is masked by ``kv_lens`` (data) and overwritten
  positionally by the next dispatch, so published prefix-cache pages
  only ever hold accepted tokens and ``kv_quant`` bytes for accepted
  positions are identical to the non-speculative path.
* PER-DRAFT GUARD — a slot whose verify segment contains any
  non-finite row fails ALONE (PDT-E018) while co-residents keep
  decoding; drilled by ``engine_draft_nan``, with
  ``engine_draft_mismatch`` forcing the rejection path (bitwise, only
  the accept rate moves).
* PROPOSERS — the model-free n-gram / prompt-lookup proposer (zero
  extra FLOPs, the serving-bench default) or a
  ``DraftModelProposer`` (small GPT/LLaMA with its OWN paged KV pool
  under the engine's free-list discipline).  ``stats`` grows
  ``spec_proposed`` / ``spec_accepted`` / ``spec_accept_rate``;
  timelines emit ``verify_window`` events and an
  accepted-tokens-per-step histogram.

Tensor parallelism & disaggregation (ISSUE 13; ``mesh=``/``tp_axis=``
kwargs / ``serving_tp`` flag; ``inference/distserve.py``):

* TP-SHARDED PROGRAMS — with a mesh, the mixed/spec/decode-window
  programs re-build over the TP axis (``models/generation.py`` TP
  section): weights column/row-split per the canonical Megatron rules
  (fused qkv re-laid-out head-major), KV data+scale pools sharded by
  kv-head (GQA-aware: ``Hk < tp`` replicates the K/V side and each
  shard attends a 1-head slice), block tables/lengths replicated, ONE
  psum at the attention output and the MLP reduce.  The scheduling
  layer is untouched — block tables and lengths are data either way —
  and greedy outputs are token-identical to the single-device engine
  (``tests/test_distserve.py``).
* POOL EXPORT/IMPORT — :meth:`export_request` serializes a resident
  request's live pages (+ scales) and scheduler state;
  :meth:`import_request` remaps them into this engine's free list
  (one compiled scatter per geometry; pages the prefix cache already
  indexes for the same token prefix are RETAINED instead of
  rewritten) and installs a decode slot.  ``DisaggServer`` builds the
  prefill->handoff->decode pipeline on top, with
  ``engine_handoff_transient`` / ``engine_decode_worker_lost`` drills
  and per-handoff spans/metrics.
* LIVE MIGRATION (ISSUE 20) — :meth:`snapshot_request` generalizes
  export to QUEUED and MID-PREFILL requests too (full scheduler
  state: tokens-so-far, cur_pos/prefill_off, deadline remaining,
  preemption/demand bookkeeping, a CRC over the KV bytes);
  :meth:`restore_request` CRC-validates the payload (a torn transfer
  is REJECTED with ``MigrationError`` PDT-E025 — the
  ``engine_snapshot_torn`` drill — and the source keeps the request),
  then funnels through the same import scatter / ``_release_slot``
  discipline; :meth:`discard_request` is the source's half of a
  completed migration — silently relinquish, no completion (unless a
  racing :meth:`cancel` owns the slot, in which case the source sweep
  finalizes it as "cancelled" and the destination drops its restore).
  A stream migrated mid-decode equals the unmigrated stream
  token-for-token: greedy decode is deterministic and batch-invariant
  and KV bytes are a pure function of the token prefix.
  ``FleetRouter`` drain / scale-in / lame-duck ride this
  (``inference/router.py``; ``serving_migration`` flag).

Compile-time program audit (ISSUE 16; ``analysis/program.py``):

* Every program the engine caches — the import scatter, COW page
  copy, decode windows, TP wrappers — is audited ONCE per (name,
  geometry) by the whole-program jaxpr analyzer at first compile
  (collective schedule consistency, donation/live-range HBM,
  recompile risk; see ``_audit_program``).  Gated by
  ``PDTPU_ANALYSIS`` (off = zero work) and never on the dispatch
  path.
"""
from __future__ import annotations

import time
import warnings
import zlib
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import (CacheIntegrityError, EngineStallError,
                           MigrationError, PageBudgetError,
                           QueueFullError)
from ..core.tensor import Tensor
from ..observability import Registry as _ObsRegistry
from ..observability import flight as _flight
from ..observability import metrics as _obs_metrics
from ..observability import slo as _slo_mod
from ..observability import tracing as _tracing
from ..observability import watchdog as _watchdog
from ..observability.serving import RegistryCounters, ServingTimelines
from ..resilience import faults
from ..resilience.serving import (SITE_DRAFT_MISMATCH, SITE_DRAFT_NAN,
                                  SITE_PAGE_PRESSURE,
                                  SITE_SNAPSHOT_TORN, DecodeGuard,
                                  dispatch_retry)
from . import speculative as _spec
from .prefix_cache import PrefixCache

__all__ = ["ContinuousBatchingEngine", "CompletedRequest"]


class _Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "done_toks", "deadline", "preemptions",
                 "requested_counted")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 deadline=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.done_toks: list[int] = []  # generated before a preemption
        self.deadline = deadline        # absolute clock() seconds | None
        self.preemptions = 0
        # prefill_tokens_requested counts each request's demand ONCE:
        # re-admissions (preempt resume, worker-lost / replica-lost
        # requeue via add_request(requeue=True)) must not re-count it,
        # or the shared_prefix/disagg bench's prefill_saved_frac
        # denominator inflates with retry traffic while
        # prefill_tokens_computed keeps metering the actual recompute
        self.requested_counted = False


class CompletedRequest:
    """Result handed back by :meth:`ContinuousBatchingEngine.step`.

    ``finish_reason`` is one of ``resilience.serving.FINISH_REASONS``:
    ``stop`` (eos), ``length`` (max_new_tokens), ``timeout`` (deadline
    expired at a step boundary), ``cancelled`` (:meth:`cancel`), or
    ``failed`` (decode guard; the coded error is on ``error``).
    ``tokens`` holds whatever was generated before the cut."""

    __slots__ = ("request_id", "prompt", "tokens", "finish_reason",
                 "error")

    def __init__(self, request_id, prompt, tokens,
                 finish_reason="length", error=None):
        self.request_id = request_id
        self.prompt = prompt          # np.int32 [S]
        self.tokens = tokens          # np.int32 [<= max_new_tokens]
        self.finish_reason = finish_reason
        self.error = error            # coded exception for "failed"

    @property
    def ok(self):
        """True for a normally-finished request (stop/length)."""
        return self.finish_reason in ("stop", "length")

    @property
    def sequence(self):
        """prompt + generated tokens, the ``generate()``-comparable row."""
        return np.concatenate([self.prompt, self.tokens])


def _payload_crc(pools) -> int:
    """CRC32 over a migration payload's KV pool bytes (ISSUE 20) —
    computed at snapshot, validated at restore, so a torn transfer is
    rejected before any destination page is allocated."""
    crc = 0
    for arr in pools:
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


class _Slot:
    __slots__ = ("req", "phase", "pages", "cur_tok", "cur_pos",
                 "prefill_ids", "prefill_off", "out_toks", "stop_len",
                 "eos", "admit_seq", "cancelled")

    def __init__(self):
        self.req = None
        self.phase = "free"           # free | prefill | decode
        self.pages = []
        self.cur_tok = 0
        self.cur_pos = 0
        self.prefill_ids = None       # prompt + replayed done_toks
        self.prefill_off = 0
        self.out_toks = []
        self.stop_len = 0
        self.eos = -1
        self.admit_seq = -1
        self.cancelled = False

    @property
    def len_written(self):
        """Tokens resident in the page pools (positions [0, len))."""
        if self.phase == "prefill":
            return self.prefill_off
        return self.cur_pos

    @property
    def done(self):
        if self.req is None:
            return True
        if self.phase == "prefill":
            return False
        if self.cur_pos + 1 >= self.stop_len:
            return True
        return bool(self.eos >= 0 and self.out_toks
                    and self.out_toks[-1] == self.eos)


class ContinuousBatchingEngine:
    """Request-level scheduler: ``add_request`` any time, ``step`` until
    it returns completions, or ``run`` to drain.  See the module
    docstring for the shape discipline and the overload policies.

    Policy knobs (engine kwargs; ``None`` falls back to the
    ``serving_*`` flags in ``core/state.py``): ``max_queue`` +
    ``queue_policy`` bound admission, ``default_deadline_ms`` applies a
    TTL to every request, ``dispatch_retries`` bounds the per-dispatch
    retry, ``prefix_cache`` gates the cross-request KV prefix cache
    (``serving_prefix_cache`` flag; ``False``/``'off'`` restores
    uncached admission bitwise), ``kv_quant`` stores KV pages int8
    with in-kernel dequant (``serving_kv_quant`` flag; default off =
    bitwise fp path), ``megakernel`` runs the decode step as ~3 fused
    Pallas dispatches per layer plus a fused sampling epilogue
    (``serving_megakernel`` flag, ISSUE 18; token streams are bitwise
    vs off, and an off-spelling restores today's decode programs
    exactly), ``spec_decode``/``spec_k``/``spec_proposer``/
    ``spec_temperature``/``spec_rejection_sampling`` drive speculative
    decoding (``serving_spec_*`` flags; greedy spec is bitwise vs
    off), ``slo`` arms declarative latency/goodput objectives over
    the engine's own timelines (``serving_slo`` flag; spec string or
    ``SLOSpec`` list — see :meth:`slo_status`), ``watchdog_ms`` arms
    the stall watchdog around every dispatch (``watchdog_stall_ms``
    flag; a stalled dispatch surfaces ``EngineStallError`` PDT-E020
    with a flight record instead of hanging).  SIZE ``watchdog_ms``
    above the worst-case dispatch INCLUDING the first compile of each
    program geometry: a deadline under compile time interrupts the
    compile mid-flight, which never caches, so the next dispatch
    recompiles and stalls again — a livelock the deadline caused.
    Warm the geometry first (or arm after warmup) when tight
    deadlines matter.  ``clock`` (tests) replaces ``time.monotonic``
    for deterministic deadline drills."""

    def __init__(self, model, *, max_slots=8, page_size=16,
                 max_seq_len=None, total_pages=None, decode_window=8,
                 prefill_chunk=64, q_block=8, pages_per_block=None,
                 max_queue=None, queue_policy=None,
                 default_deadline_ms=None, dispatch_retries=None,
                 prefix_cache=None, kv_quant=None, megakernel=None,
                 spec_decode=None,
                 spec_k=None, spec_proposer=None, spec_temperature=None,
                 spec_rejection_sampling=None, spec_seed=0, clock=None,
                 mesh=None, tp_axis=None, slo=None, watchdog_ms=None):
        from ..core import state as _state
        from ..models.generation import (_decode_fn, _ragged_fn,
                                         _zero_pool)
        cfg = model.cfg
        self.model = model
        model.eval()   # the engine owns its model: serving is eval-mode
        self._decode, _, self._hard_limit = _decode_fn(model)
        self._ragged = _ragged_fn(model)
        # tensor parallelism (ISSUE 13): shard the two compiled serving
        # programs over a mesh axis — weights column/row-split per the
        # canonical Megatron rules, KV pools sharded by kv-head, block
        # tables/lengths replicated; greedy outputs token-identical to
        # the single-device engine (models/generation.py TP section).
        # ``mesh=None`` with the ``serving_tp`` flag > 1 builds a
        # default 1-axis mesh over the first ``serving_tp`` devices.
        tp_deg = int(_state.get_flag("serving_tp"))
        if mesh is None and tp_deg > 1:
            import jax as _jax
            devs = _jax.devices()
            if len(devs) < tp_deg:
                raise ValueError(
                    f"serving_tp={tp_deg} but only {len(devs)} devices "
                    "are visible")
            from jax.sharding import Mesh as _Mesh
            mesh = _Mesh(np.asarray(devs[:tp_deg]), ("tp",))
        self._jmesh = None
        self.tp_axis = None
        self._tpp = None
        if mesh is not None:
            jmesh = getattr(mesh, "jmesh", mesh)   # ProcessMesh or Mesh
            if tp_axis is None:
                axes = tuple(jmesh.axis_names)
                if len(axes) == 1:
                    tp_axis = axes[0]
                elif "tp" in axes:
                    tp_axis = "tp"
                else:
                    raise ValueError(
                        f"mesh has axes {axes}: pass tp_axis= to pick "
                        "the tensor-parallel one")
            from ..models.generation import tp_shard_params
            # sharded param extraction is a read-only snapshot cached
            # ON the model per (devices, axis): prefill/decode worker
            # engines sharing one model share one copy of the shards
            key = (tuple(d.id for d in jmesh.devices.flat),
                   str(tp_axis))
            tcache = model.__dict__.setdefault("_tp_params_cache", {})
            tpp = tcache.get(key)
            if tpp is None:
                tpp = tp_shard_params(model, jmesh, tp_axis)
                tcache[key] = tpp
            self._jmesh = jmesh
            self.tp_axis = str(tp_axis)
            self._tpp = tpp
        self.tp = 1 if self._tpp is None else self._tpp.meta["tp"]
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self._hard_limit:
            self.max_seq_len = min(self.max_seq_len, cfg.max_seq_len)
        self.decode_window = int(decode_window)
        self.q_block = int(q_block)
        self.prefill_chunk = max(self.q_block, int(prefill_chunk))
        self.pages_per_block = pages_per_block
        # per-slot page-table width covers the engine's length cap
        self.np_per_seq = -(-self.max_seq_len // self.page_size)
        if total_pages is None:
            total_pages = 1 + self.max_slots * self.np_per_seq
        self.total_pages = int(total_pages)
        # speculative decoding (ISSUE 9; inference/speculative.py):
        # decode slots submit K drafts + the current token as one
        # ragged verify segment through the mixed program and advance
        # by the accepted length — greedy outputs bitwise-identical to
        # spec off, only tokens-per-dispatch moves
        sd = (_state.get_flag("serving_spec_decode")
              if spec_decode is None else spec_decode)
        self.spec_decode = bool(sd)
        self.spec_k = int(_state.get_flag("serving_spec_k")
                          if spec_k is None else spec_k)
        st_ = (_state.get_flag("serving_spec_temperature")
               if spec_temperature is None else spec_temperature)
        self.spec_temperature = float(st_)
        rs = (_state.get_flag("serving_spec_rejection_sampling")
              if spec_rejection_sampling is None
              else spec_rejection_sampling)
        self.spec_rejection_sampling = bool(rs)
        self._proposer = None
        self._spec_rng = np.random.default_rng(int(spec_seed))
        if self.spec_decode:
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, "
                                 f"got {self.spec_k}")
            from .speculative import make_proposer
            self._proposer = make_proposer(
                _state.get_flag("serving_spec_proposer")
                if spec_proposer is None else spec_proposer)
            self._proposer.bind(self)
        # token budget of the mixed step: one q_block per slot (the
        # ongoing decodes; under spec_decode a slot's verify segment is
        # up to spec_k+1 rows, q_block-padded) + the prefill chunk
        seg_rows = self.q_block
        if self.spec_decode:
            seg_rows = max(seg_rows, -(-(self.spec_k + 1)
                                       // self.q_block) * self.q_block)
        self.token_budget = (self.max_slots * seg_rows
                             + self.prefill_chunk)

        # overload policies (kwarg > flag; 0 flag values mean "off")
        self.max_queue = int(_state.get_flag("serving_max_queue")
                             if max_queue is None else max_queue)
        self.queue_policy = str(_state.get_flag("serving_queue_policy")
                                if queue_policy is None else queue_policy)
        if self.queue_policy not in ("reject", "block"):
            raise ValueError(
                f"queue_policy must be 'reject' or 'block', "
                f"got {self.queue_policy!r}")
        dl = float(_state.get_flag("serving_deadline_ms")
                   if default_deadline_ms is None else default_deadline_ms)
        self.default_deadline_ms = dl if dl > 0 else None
        self.dispatch_retries = int(
            _state.get_flag("serving_dispatch_retries")
            if dispatch_retries is None else dispatch_retries)
        self._clock = time.monotonic if clock is None else clock
        self._guard = DecodeGuard(self.max_slots)

        kq = (_state.get_flag("serving_kv_quant")
              if kv_quant is None else kv_quant)
        if isinstance(kq, str):
            # strict parse: kv_quant changes numerics, so a typo must
            # not silently enable lossy int8 KV
            if kq.lower() in _state.KV_QUANT_ON_SPELLINGS:
                kq = True
            elif kq.lower() in _state.KV_QUANT_OFF_SPELLINGS:
                kq = False
            else:
                raise ValueError(
                    f"kv_quant={kq!r}: expected one of "
                    f"{_state.KV_QUANT_ON_SPELLINGS} or "
                    f"{_state.KV_QUANT_OFF_SPELLINGS}")
        self.kv_quant = bool(kq)
        mk = (_state.get_flag("serving_megakernel")
              if megakernel is None else megakernel)
        if isinstance(mk, str):
            # same strict-spelling discipline as kv_quant: the
            # megakernel swaps the entire compiled decode program, so
            # a typo must not silently change which kernels serve
            # tokens (ISSUE 18; PDT120 flags overload-tuned engines
            # built with an off-spelling)
            if mk.lower() in _state.MEGAKERNEL_ON_SPELLINGS:
                mk = True
            elif mk.lower() in _state.MEGAKERNEL_OFF_SPELLINGS:
                mk = False
            else:
                raise ValueError(
                    f"megakernel={mk!r}: expected one of "
                    f"{_state.MEGAKERNEL_ON_SPELLINGS} or "
                    f"{_state.MEGAKERNEL_OFF_SPELLINGS}")
        self.megakernel = bool(mk)
        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        shape = (n_kv, self.total_pages, self.page_size, cfg.head_dim)
        # int8 KV (ISSUE 7): data pools go int8 and per-page scale
        # side-pools [Hk, P, page_size] APPEND to the cache list —
        # every downstream consumer (COW copy, decode-window donation,
        # program signatures) treats the list opaquely, and the model
        # forwards split it by length (models/generation._split_caches),
        # so block tables, the prefix cache and preempt-requeue carry
        # the scales without knowing they exist.
        kv_dtype = "int8" if self.kv_quant else "float32"
        pools = list(_zero_pool(shape, 2 * cfg.num_layers, kv_dtype))
        if self.kv_quant:
            pools += list(_zero_pool(shape[:3], 2 * cfg.num_layers,
                                     "float32"))
        if self._tpp is not None:
            # pools live sharded by kv-head over the TP axis (or fully
            # replicated on the GQA Hk < tp path) — page ids and block
            # tables are pool-wide either way
            import jax as _jax
            from jax.sharding import NamedSharding as _NS

            from ..models.generation import tp_cache_spec
            cspec = tp_cache_spec(self._tpp.meta, self.tp_axis)
            pools = [_jax.device_put(p, _NS(self._jmesh, cspec))
                     for p in pools]
        self._caches = [Tensor(a) for a in pools]
        # bytes per page across all layers (data + scales): the
        # serving-roofline accounting the quant path halves
        itemsize = 1 if self.kv_quant else 4
        self._page_bytes = 2 * cfg.num_layers * n_kv * self.page_size \
            * (cfg.head_dim * itemsize + (4 if self.kv_quant else 0))
        self._free_pages = deque(range(1, self.total_pages))  # 0 = null
        pc = (_state.get_flag("serving_prefix_cache")
              if prefix_cache is None else prefix_cache)
        if isinstance(pc, str):
            pc = pc.lower() not in _state.PREFIX_CACHE_OFF_SPELLINGS
        self.prefix_cache_enabled = bool(pc)
        self._cache = PrefixCache(self.page_size, self._free_pages,
                                  enabled=self.prefix_cache_enabled,
                                  total_pages=self.total_pages)
        self._bt = np.zeros((self.max_slots, self.np_per_seq), np.int32)
        self._slots = [_Slot() for _ in range(self.max_slots)]
        self._queue: deque[_Request] = deque()
        self._early: list[CompletedRequest] = []  # finalized off-dispatch
        self._next_rid = 0
        self._admit_counter = 0
        self._step_fn = None
        self._mixed_fn = None
        self._spec_fn = None
        self._cow_fn = None
        self._import_fn = None
        self._decode_exe = None
        # counters, RE-BACKED by a private observability registry
        # (ISSUE 8): the ``stats`` property reads the same keys/values
        # as the old plain dict (always=True counters — the stats
        # contract predates the metrics flag), while ``metrics()``
        # exposes them alongside the timeline histograms.  The registry
        # is per-engine so concurrent engines never alias counters.
        self._registry = _ObsRegistry("serving_engine")
        self._stats = RegistryCounters(self._registry, (
            "admitted", "retired", "steps", "mixed_steps",
            "decode_dispatches", "tokens_generated", "pages_allocated",
            "peak_pages_in_use", "preemptions", "timeouts", "cancelled",
            "failed", "rejected", "retries", "cache_hits",
            "cache_hit_tokens", "prefill_tokens_requested",
            "prefill_tokens_computed"))
        # speculative counters (ISSUE 9) live in their OWN block so the
        # stats property can APPEND them after every pre-existing key —
        # the stats contract is keys/order-stable, new keys at the end
        self._spec_stats = RegistryCounters(self._registry, (
            "spec_proposed", "spec_accepted"))
        # live migration (ISSUE 20) — own block, APPENDED after the
        # spec keys by the stats property for the same reason
        self._mig_stats = RegistryCounters(self._registry, (
            "migrated_in", "migrated_out"))
        # per-request serving timelines (queue/TTFT/TPOT histograms +
        # structured events for the flight recorder), on the engine's
        # deadline clock so tests can drive them deterministically
        self._tl = ServingTimelines(self._registry, clock=self._clock)
        # live gauges read LAZILY at snapshot time (no work per step)
        reg = self._registry
        reg.gauge("serving.pages_in_use").set_function(
            self._pages_in_use)
        reg.gauge("serving.pages_free").set_function(
            lambda: len(self._free_pages))
        reg.gauge("serving.cached_pages").set_function(
            lambda: self._cache.cached_pages)
        reg.gauge("serving.queue_depth").set_function(
            lambda: len(self._queue))
        reg.gauge("serving.kv_page_bytes").set_function(
            lambda: self._page_bytes)
        # SLO guardrails (ISSUE 14, observability/slo.py): declarative
        # objectives over this engine's OWN timeline histograms,
        # evaluated over sliding windows once per scheduling step
        # (throttled — one clock compare when the interval hasn't
        # elapsed).  A multi-window burn-rate breach emits slo.breach
        # and dumps a flight record.  The stall watchdog
        # (observability/watchdog.py) arms every dispatch when
        # watchdog_ms > 0: a dispatch past the deadline gets its
        # thread stacks + flight record captured and a coded
        # EngineStallError injected instead of hanging step() forever.
        wd_ms = float(_state.get_flag("watchdog_stall_ms")
                      if watchdog_ms is None else watchdog_ms)
        self.watchdog_ms = wd_ms if wd_ms > 0 else 0.0
        slo_cfg = (_state.get_flag("serving_slo") if slo is None
                   else slo)
        specs = _slo_mod.parse_slo(slo_cfg)
        self._slo = None
        if specs:
            self._slo = _slo_mod.SLOEngine(
                self._registry, specs, clock=self._clock,
                on_breach=self._on_slo_breach)

    # ------------------------------------------------------------ API --
    def _pages_in_use(self) -> int:
        """Pages held by resident slots: the usable pool minus free
        minus cached — ONE home for the formula (the stats property,
        the lazy gauge and peak tracking all read it here)."""
        return (self.total_pages - 1 - len(self._free_pages)
                - self._cache.cached_pages)

    @property
    def stats(self):
        """Health snapshot: the lifetime counters plus live gauges
        (``pages_in_use``/``pages_free``/``cached_pages``/
        ``queue_depth``).  ``pages_in_use + pages_free + cached_pages``
        always sums to the usable pool (``total_pages - 1``)."""
        d = self._stats.as_dict()
        d["cached_pages"] = self._cache.cached_pages
        d["evictions"] = self._cache.evictions
        d["pages_in_use"] = self._pages_in_use()
        d["pages_free"] = len(self._free_pages)
        d["queue_depth"] = len(self._queue)
        # KV byte accounting (ISSUE 7): per-page bytes across all
        # layers including int8 scale side-pools — the quant path's
        # halved-bytes acceptance gate reads these
        d["kv_quant"] = self.kv_quant
        d["kv_page_bytes"] = self._page_bytes
        d["kv_bytes_in_use"] = d["pages_in_use"] * self._page_bytes
        # speculative decoding (ISSUE 9) — APPENDED: every pre-existing
        # key keeps its position (the backward-compat test pins that)
        d["spec_proposed"] = self._spec_stats["spec_proposed"]
        d["spec_accepted"] = self._spec_stats["spec_accepted"]
        d["spec_accept_rate"] = round(
            d["spec_accepted"] / d["spec_proposed"], 4) \
            if d["spec_proposed"] else 0.0
        # live migration (ISSUE 20) — APPENDED after the spec keys
        d["migrated_in"] = self._mig_stats["migrated_in"]
        d["migrated_out"] = self._mig_stats["migrated_out"]
        return d

    def metrics(self) -> dict:
        """Full observability snapshot (nested JSON): every ``stats``
        counter plus the derived serving timelines — queue-time, TTFT,
        TPOT and decode-tokens-per-window histograms, finish-reason
        labeled counters, per-dispatch latency.  See
        ``paddle_tpu.observability`` for the snapshot format."""
        return self._registry.snapshot()

    def render_prometheus(self) -> str:
        """This engine's metrics in Prometheus text format (the SLO
        budget-remaining / burn-rate gauges included when SLOs are
        armed)."""
        return self._registry.render_prometheus()

    def slo_status(self) -> list:
        """Per-spec SLO status (``observability/slo.py``): name, the
        windowed value vs target, fast/slow burn rates, error budget
        remaining, and the multi-window ``breached`` verdict.  Empty
        when no SLOs are armed (``serving_slo`` flag / ``slo=`` kwarg)
        or metrics are off."""
        if self._slo is None:
            return []
        return self._slo.status()

    def _on_slo_breach(self, status):
        """Breach hook: the SLOEngine already emitted ``slo.breach``
        into the ring; dump the flight record so the postmortem holds
        the minutes that burned the budget."""
        _flight.dump("slo_breach", extra=dict(status))

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    request_id=None, deadline_ms=None, requeue=False):
        prompt = np.asarray(
            prompt.numpy() if isinstance(prompt, Tensor) else prompt,
            np.int32).reshape(-1)
        total = prompt.size + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens > engine max_seq_len "
                f"{self.max_seq_len}")
        # eager page-budget rejection: a request whose full length can
        # never fit the pool must fail HERE, not poison the queue and
        # crash step() after everything ahead of it drains
        need_full = -(-total // self.page_size)
        if need_full > self.total_pages - 1:
            self._stats["rejected"] += 1
            raise PageBudgetError(
                f"request needs {need_full} pages but the pool only has "
                f"{self.total_pages - 1}; raise total_pages or lower "
                f"max_new_tokens [{PageBudgetError.error_code}]")
        if self.max_queue and len(self._queue) >= self.max_queue:
            if self.queue_policy == "reject":
                self._stats["rejected"] += 1
                raise QueueFullError(
                    f"admission queue full ({self.max_queue}); shed load "
                    f"or use queue_policy='block' "
                    f"[{QueueFullError.error_code}]")
            # block: drive the engine until the queue drains one slot.
            # Admissible requests always drain (see module docstring),
            # so this terminates; the guard catches a wedged engine.
            for _ in range(1_000_000):
                if len(self._queue) < self.max_queue or not self.has_work:
                    break
                self._early.extend(self.step())
            else:
                raise RuntimeError("queue_policy='block': engine made no "
                                   "progress draining the queue")
        if request_id is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = request_id
            if isinstance(rid, int):  # auto ids must never collide
                self._next_rid = max(self._next_rid, rid + 1)
            in_flight = {r.rid for r in self._queue} | {
                s.req.rid for s in self._slots if s.req is not None}
            if rid in in_flight:
                raise ValueError(f"request_id {rid!r} already in flight")
        dl_ms = (self.default_deadline_ms
                 if deadline_ms is None else float(deadline_ms))
        deadline = (self._clock() + dl_ms / 1e3) if dl_ms else None
        req = _Request(
            rid, prompt, max_new_tokens,
            -1 if eos_token_id is None else int(eos_token_id), deadline)
        # requeue=True: a coordinator re-submitting a request it
        # already counted (disagg worker-lost, fleet replica-lost) —
        # its demand is already in prefill_tokens_requested
        req.requested_counted = bool(requeue)
        self._queue.append(req)
        self._tl.enqueued(rid, prompt.size, max_new_tokens)
        return rid

    def cancel(self, rid) -> bool:
        """Cancel a queued or resident request; its CompletedRequest
        (``finish_reason == "cancelled"``, tokens generated so far)
        surfaces from the next :meth:`step`. False when ``rid`` is not
        in flight (already completed or unknown)."""
        for i, r in enumerate(self._queue):
            if r.rid == rid:
                del self._queue[i]
                self._stats["cancelled"] += 1
                self._early.append(CompletedRequest(
                    rid, r.prompt, np.asarray(r.done_toks, np.int32),
                    "cancelled"))
                self._tl.retired(rid, "cancelled", len(r.done_toks),
                                 r.preemptions)
                return True
        for s in self._slots:
            if s.req is not None and s.req.rid == rid and not s.cancelled:
                s.cancelled = True   # finalized at the next step boundary
                return True
        return False

    def pending_requests(self):
        """Request ids still in flight (resident slots, then queued) —
        what a budget-exhausted :meth:`run` leaves behind."""
        out = [s.req.rid for s in self._slots if s.req is not None]
        out.extend(r.rid for r in self._queue)
        return out

    def cached_prefix_tokens(self, ids) -> int:
        """Longest page-aligned prefix of ``ids`` already indexed in
        this engine's radix prefix cache, in TOKENS (0 with caching
        off) — the fleet router's affinity-placement query
        (``inference/router.py``): route a prompt to the replica whose
        trie already holds its prefix and admission maps those pages
        instead of recomputing them.  Read-only apart from refreshing
        the matched path's LRU recency; never changes outputs."""
        ids = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids,
            np.int32).reshape(-1)
        return len(self._cache.match(ids)) * self.page_size

    @property
    def has_work(self):
        return bool(self._queue) or bool(self._early) or any(
            s.req is not None for s in self._slots)

    def run(self, max_steps=10000):
        """Drain: step until every queued/resident request completes.
        Returns {request_id: CompletedRequest} in completion order.
        Warns (once) when ``max_steps`` is exhausted with requests
        still in flight — see :meth:`pending_requests`."""
        done = {}
        for _ in range(max_steps):
            if not self.has_work:
                break
            for c in self.step():
                done[c.request_id] = c
        for c in self._early:   # finalized after the last step ran
            done[c.request_id] = c
        self._early.clear()
        if self.has_work:
            pend = self.pending_requests()
            warnings.warn(
                f"ContinuousBatchingEngine.run: step budget "
                f"({max_steps}) exhausted with {len(pend)} request(s) "
                f"unfinished — engine.pending_requests() lists them; "
                f"raise max_steps or check admission (queue depth "
                f"{len(self._queue)})", RuntimeWarning, stacklevel=2)
        return done

    # ------------------------------------- pool export / import -------
    # the KV-page handoff substrate of disaggregated prefill/decode
    # serving (inference/distserve.py): export serializes ONLY a
    # request's live pages (+ scale side-pools) and its scheduler
    # state; import remaps them into this engine's free list and
    # installs a resident decode slot.  Export is read-only (the
    # source engine's publish-at-retire / prefix-cache discipline is
    # untouched); import allocates through the prefix cache, so pages
    # this engine already holds for the same token prefix are RETAINED
    # instead of rewritten — cached prefixes survive handoff.

    def export_request(self, rid):
        """Serialize a resident decode-phase request for handoff.
        Returns a payload dict (numpy KV bytes + state); the slot
        stays resident — the caller decides when it retires."""
        for s in self._slots:
            if s.req is not None and s.req.rid == rid:
                break
        else:
            raise KeyError(f"request {rid!r} is not resident")
        if s.phase != "decode":
            raise ValueError(
                f"request {rid!r} is still prefilling — export after "
                "its first token")
        n = s.len_written
        n_pages = -(-n // self.page_size)
        pages = np.asarray(s.pages[:n_pages], np.int64)
        pools = [np.asarray(c._read()[:, pages]) for c in self._caches]
        return {
            "rid": rid,
            "prompt": np.asarray(s.req.prompt, np.int32),
            "done_toks": [int(t) for t in s.out_toks],
            "cur_tok": int(s.cur_tok),
            "cur_pos": int(s.cur_pos),
            "eos": int(s.eos),
            "len_written": int(n),
            "n_pages": int(n_pages),
            "page_size": self.page_size,
            "kv_quant": self.kv_quant,
            "pools": pools,
        }

    def _get_import_fn(self):
        if self._import_fn is None:
            key = ("import", len(self._caches)) + self._geometry()
            cache = self._program_cache()
            self._import_fn = cache.get(key)
        if self._import_fn is None:
            n = len(self._caches)
            from ..models.generation import make_import_scatter
            shardings = None
            if self._tpp is not None:
                from jax.sharding import NamedSharding as _NS

                from ..models.generation import tp_cache_spec
                cspec = tp_cache_spec(self._tpp.meta, self.tp_axis)
                shardings = [_NS(self._jmesh, cspec)
                             for _ in range(n)]
            self._import_fn = make_import_scatter(n, shardings)
            self._program_cache()[("import", len(self._caches))
                                  + self._geometry()] = self._import_fn
        return self._import_fn

    def _scatter_payload(self, pages, n_matched, n_imp, pools):
        """Scatter a payload's FRESH page rows (payload slots
        ``[n_matched, n_imp)``; prefix-cache-matched pages already hold
        identical bytes) into the pool pages named by ``pages`` — ONE
        compiled dispatch per geometry (the page-id vector is traced
        data; idx/payload pad to the table width so one program serves
        every import/restore of this geometry).  On failure every page
        reference in ``pages`` is released before re-raising: no slot
        owns them yet, so the ``_release_slot`` funnel could never
        return them and each caller retry would leak ``n_alloc``
        pages otherwise."""
        NP = self.np_per_seq
        idx = np.zeros(NP, np.int32)
        sel = np.zeros(NP, np.int64)          # payload page slot -> row
        take = np.zeros(NP, bool)
        for j in range(n_matched, n_imp):
            idx[j] = pages[j]
            sel[j] = j
            take[j] = True
        if not take.any():   # a full prefix-cache hit scatters nothing
            return
        pads = []
        for arr in pools:
            pad = np.zeros(arr.shape[:1] + (NP,) + arr.shape[2:],
                           arr.dtype)
            pad[:, take] = arr[:, sel[take]]
            pads.append(pad)
        fn = self._get_import_fn()
        vals = [c._read() for c in self._caches]
        self._audit_program(
            "import", fn,
            (jnp.asarray(idx), *vals,
             *[jnp.asarray(p) for p in pads]),
            donated=tuple(range(1, 1 + len(vals))))

        def _import_call():
            if any(getattr(v, "is_deleted", lambda: False)()
                   for v in vals):
                raise RuntimeError(
                    "import dispatch failed after its KV buffers "
                    "were donated; a mid-execution transient is "
                    "unrecoverable at this layer — re-create the "
                    "engine and re-submit the pending requests")
            return fn(jnp.asarray(idx), *vals,
                      *[jnp.asarray(p) for p in pads])

        try:
            new = self._dispatch("import", _import_call)
        except Exception:
            self._cache.release(pages)
            raise
        for t, v in zip(self._caches, new):
            t._data = v
            t._node = None

    def import_request(self, payload, max_new_tokens, request_id=None,
                       deadline_ms=None):
        """Install an exported (prefilled) request as a resident
        DECODE slot: allocate pages, scatter the payload's KV bytes
        into them (ONE compiled dispatch per geometry; the page-id
        vector is traced data), and seed the slot's scheduler state.
        Pages this engine's prefix cache already indexes for the same
        token prefix are retained instead of scattered.  Returns the
        request id, or ``None`` when no slot / not enough pages are
        free right now (retry after a step)."""
        if payload["page_size"] != self.page_size \
                or payload["kv_quant"] != self.kv_quant \
                or len(payload["pools"]) != len(self._caches):
            raise ValueError(
                "import_request: incompatible KV layout (page_size/"
                "kv_quant/pool count must match the exporting engine)")
        prompt = np.asarray(payload["prompt"], np.int32)
        done = list(payload["done_toks"])
        cur_pos = int(payload["cur_pos"])
        stop = prompt.size + int(max_new_tokens)
        if stop > self.max_seq_len:
            raise ValueError(
                f"request needs {stop} tokens > engine max_seq_len "
                f"{self.max_seq_len}")
        if len(done) >= int(max_new_tokens):
            raise ValueError(
                "import_request: request already complete — finalize "
                "it on the coordinator instead of importing")
        rid = payload["rid"] if request_id is None else request_id
        if isinstance(rid, int):   # keep add_request's auto ids clear
            self._next_rid = max(self._next_rid, rid + 1)
        in_flight = {r.rid for r in self._queue} | {
            s.req.rid for s in self._slots if s.req is not None}
        if rid in in_flight:
            raise ValueError(f"request_id {rid!r} already in flight")
        need_full = -(-stop // self.page_size)
        if need_full > self.total_pages - 1:
            self._stats["rejected"] += 1
            raise PageBudgetError(
                f"request needs {need_full} pages but the pool only "
                f"has {self.total_pages - 1} "
                f"[{PageBudgetError.error_code}]")
        for b, s in enumerate(self._slots):
            if s.req is None:
                break
        else:
            return None                       # no free slot: retry
        n_imp = int(payload["n_pages"])
        ps = self.page_size
        target = max(cur_pos, min(cur_pos + 1, stop))
        n_need = max(n_imp, max(1, -(-target // ps)))
        # decode-side prefix reuse: full pages this engine already
        # indexes for the written token prefix ride as-is (the bytes
        # are identical by construction — KV content is a pure
        # function of the token prefix)
        ids_written = np.concatenate(
            [prompt, np.asarray(done, np.int32)])[:cur_pos]
        matched = self._cache.match(ids_written)[:n_imp]
        self._cache.retain(matched)
        n_alloc = n_need - len(matched)
        if n_alloc > self._cache.available():
            self._cache.release(matched)
            return None                       # pool pressure: retry
        alloc = [self._cache.acquire(key=str(rid))
                 for _ in range(n_alloc)]
        pages = matched + alloc
        self._scatter_payload(pages, len(matched), n_imp,
                              payload["pools"])
        req = _Request(rid, prompt, int(max_new_tokens),
                       int(payload["eos"]),
                       (self._clock() + float(deadline_ms) / 1e3)
                       if deadline_ms else None)
        s.req = req
        s.phase = "decode"
        s.pages = pages
        s.out_toks = done
        s.cur_tok = int(payload["cur_tok"])
        s.cur_pos = cur_pos
        s.stop_len = stop
        s.eos = int(payload["eos"])
        s.admit_seq = self._admit_counter
        self._admit_counter += 1
        self._bt[b, :] = 0
        self._bt[b, :len(pages)] = pages
        self._stats["admitted"] += 1
        self._stats["pages_allocated"] += len(alloc)
        if matched:
            self._stats["cache_hits"] += 1
            self._stats["cache_hit_tokens"] += len(matched) * ps
        self._tl.enqueued(rid, prompt.size, int(max_new_tokens))
        self._tl.admitted(rid, b, cached_tokens=len(matched) * ps,
                          resume_len=cur_pos)
        for _ in done:      # tokens the prefill side already produced
            self._tl.token(rid)
        self._note_peak()
        return rid

    # ------------------------------------------- live migration -------
    # ISSUE 20: the full-request snapshot/restore/discard triple the
    # fleet router's drain / scale-in / lame-duck paths ride.  Snapshot
    # generalizes export to queued and mid-prefill requests (full
    # scheduler state + a CRC over the KV bytes); restore validates and
    # funnels through the import scatter; discard is the source's half
    # of a completed migration — silent, no CompletedRequest, and it
    # DEFERS to a racing cancel() (the sweep owns cancelled slots).

    def _snapshot_state(self, req, phase):
        rem = None
        if req.deadline is not None:
            rem = (req.deadline - self._clock()) * 1e3
        return {
            "kind": "snapshot",
            "version": 1,
            "phase": phase,
            "rid": req.rid,
            "prompt": np.asarray(req.prompt, np.int32),
            "max_new_tokens": int(req.max_new_tokens),
            "eos": int(req.eos_token_id),
            "deadline_ms": rem,
            "preemptions": int(req.preemptions),
            "requested_counted": bool(req.requested_counted),
            "page_size": self.page_size,
            "kv_quant": self.kv_quant,
        }

    def snapshot_request(self, rid):
        """Serialize a QUEUED or RESIDENT request for live migration
        (ISSUE 20).  The payload extends :meth:`export_request` with
        the full scheduler state — phase, tokens-so-far, deadline
        REMAINING (absolute deadlines don't survive a clock change of
        engine), preemption/demand bookkeeping — plus a CRC over the
        KV pool bytes so :meth:`restore_request` rejects a torn
        transfer.  Queued requests carry no pools; mid-prefill
        residents carry the pages written so far (``prefill_off``
        positions), so a planned preemption loses zero prefill work.
        The request stays here untouched — the caller pairs a
        successful restore with :meth:`discard_request`.  Raises
        ``KeyError`` when ``rid`` is not in flight and ``ValueError``
        for a slot migration must skip (cancelled: the sweep owns it;
        done: it retires on the next step)."""
        for r in self._queue:
            if r.rid == rid:
                p = self._snapshot_state(r, "queued")
                p.update(done_toks=[int(t) for t in r.done_toks],
                         len_written=0, n_pages=0, pools=[],
                         crc=_payload_crc([]))
                return p
        for s in self._slots:
            if s.req is not None and s.req.rid == rid:
                break
        else:
            raise KeyError(f"request {rid!r} is not queued or resident")
        if s.cancelled:
            raise ValueError(
                f"request {rid!r} is cancelled — the sweep finalizes "
                "it on this engine; migration must skip it")
        if s.phase == "decode" and s.done:
            raise ValueError(
                f"request {rid!r} is complete — it retires on the "
                "next step; migration must skip it")
        n = s.len_written
        n_pages = -(-n // self.page_size)
        pages = np.asarray(s.pages[:n_pages], np.int64)
        pools = [np.asarray(c._read()[:, pages]) for c in self._caches]
        p = self._snapshot_state(s.req, s.phase)
        p.update(done_toks=[int(t) for t in s.out_toks],
                 cur_tok=int(s.cur_tok), cur_pos=int(s.cur_pos),
                 len_written=int(n), n_pages=int(n_pages),
                 pools=pools, crc=_payload_crc(pools))
        if s.phase == "prefill":
            p["prefill_off"] = int(s.prefill_off)
        return p

    def restore_request(self, payload, max_new_tokens=None,
                        request_id=None, deadline_ms=None):
        """Install a migrated :meth:`snapshot_request` payload —
        queued payloads re-enter admission (demand already counted on
        the source rides the ``requeue`` contract), resident payloads
        funnel through the import scatter and land a slot in the
        SAME phase at the same position, so the continued stream is
        bitwise the unmigrated one.  The payload CRC is validated
        first: a torn transfer (``engine_snapshot_torn`` drill) raises
        ``MigrationError`` (PDT-E025) before any page is allocated and
        the source keeps the request.  Returns the request id, or
        ``None`` when no slot / not enough pages are free right now
        (retry after a step); raises ``ValueError`` for a payload
        whose source cancelled it (the destination drops the
        restore)."""
        phase = payload.get("phase", "decode")
        rid = payload["rid"] if request_id is None else request_id
        if payload.get("cancelled"):
            raise ValueError(
                f"request {rid!r} was cancelled on the source — "
                "dropping the restore (the source sweep finalizes it)")
        pools = list(payload.get("pools") or [])
        if pools and faults.check(SITE_SNAPSHOT_TORN, key=str(rid)):
            # drill: the transfer tore mid-flight — flip one KV byte
            # on a local copy so CRC validation catches it below
            torn = np.array(pools[0], copy=True)
            if torn.nbytes:
                torn.view(np.uint8).reshape(-1)[0] ^= 0xFF
            pools[0] = torn
        crc = payload.get("crc")
        if crc is not None and _payload_crc(pools) != int(crc):
            raise MigrationError(
                f"restore_request: snapshot payload for request "
                f"{rid!r} failed CRC validation (torn transfer) — "
                f"restore rejected, the source keeps the request "
                f"[{MigrationError.error_code}]")
        mnt = int(payload["max_new_tokens"]
                  if max_new_tokens is None else max_new_tokens)
        if deadline_ms is None:
            deadline_ms = payload.get("deadline_ms")
        if phase == "queued":
            eos = int(payload["eos"])
            out = self.add_request(
                payload["prompt"], mnt, None if eos < 0 else eos,
                request_id=rid, deadline_ms=deadline_ms,
                requeue=bool(payload.get("requested_counted")))
            req = self._queue[-1]
            req.done_toks = [int(t) for t in payload.get("done_toks",
                                                         [])]
            req.preemptions = int(payload.get("preemptions", 0))
            self._mig_stats["migrated_in"] += 1
            self._tl.migrated(out, "in", phase="queued")
            return out
        if phase == "decode":
            pl = dict(payload)
            pl["pools"] = pools
            out = self.import_request(pl, mnt, request_id=request_id,
                                      deadline_ms=deadline_ms)
            if out is None:
                return None
            for s in self._slots:
                if s.req is not None and s.req.rid == out:
                    s.req.preemptions = int(
                        payload.get("preemptions", 0))
                    s.req.requested_counted = bool(
                        payload.get("requested_counted", True))
                    break
            self._mig_stats["migrated_in"] += 1
            self._tl.migrated(out, "in",
                              pages=int(payload.get("n_pages", 0)),
                              phase="decode")
            return out
        # phase == "prefill": land a MID-PREFILL resident — the pages
        # written so far ship warm; the destination's chunked prefill
        # resumes at prefill_off exactly (arbitrary offsets are normal
        # there: budget-limited chunks split mid-page already), so no
        # prefill work is recomputed and the stream stays bitwise
        if payload["page_size"] != self.page_size \
                or payload["kv_quant"] != self.kv_quant \
                or len(pools) != len(self._caches):
            raise ValueError(
                "restore_request: incompatible KV layout (page_size/"
                "kv_quant/pool count must match the source engine)")
        prompt = np.asarray(payload["prompt"], np.int32)
        done = [int(t) for t in payload["done_toks"]]
        off = int(payload["prefill_off"])
        stop = prompt.size + mnt
        if stop > self.max_seq_len:
            raise ValueError(
                f"request needs {stop} tokens > engine max_seq_len "
                f"{self.max_seq_len}")
        if isinstance(rid, int):
            self._next_rid = max(self._next_rid, rid + 1)
        in_flight = {r.rid for r in self._queue} | {
            s.req.rid for s in self._slots if s.req is not None}
        if rid in in_flight:
            raise ValueError(f"request_id {rid!r} already in flight")
        need_full = -(-stop // self.page_size)
        if need_full > self.total_pages - 1:
            self._stats["rejected"] += 1
            raise PageBudgetError(
                f"request needs {need_full} pages but the pool only "
                f"has {self.total_pages - 1} "
                f"[{PageBudgetError.error_code}]")
        for b, s in enumerate(self._slots):
            if s.req is None:
                break
        else:
            return None                       # no free slot: retry
        ps = self.page_size
        n_imp = int(payload["n_pages"])
        ids = (np.concatenate([prompt, np.asarray(done, np.int32)])
               if done else prompt)
        resume = int(ids.size)
        target = max(resume, min(resume + 1, stop))
        n_need = max(n_imp, max(1, -(-target // ps)))
        matched = self._cache.match(ids[:off])[:n_imp]
        self._cache.retain(matched)
        n_alloc = n_need - len(matched)
        if n_alloc > self._cache.available():
            self._cache.release(matched)
            return None                       # pool pressure: retry
        alloc = [self._cache.acquire(key=str(rid))
                 for _ in range(n_alloc)]
        pages = matched + alloc
        self._scatter_payload(pages, len(matched), n_imp, pools)
        req = _Request(rid, prompt, mnt, int(payload["eos"]),
                       (self._clock() + float(deadline_ms) / 1e3)
                       if deadline_ms else None)
        req.done_toks = done
        req.preemptions = int(payload.get("preemptions", 0))
        req.requested_counted = bool(
            payload.get("requested_counted", True))
        s.req = req
        s.phase = "prefill"
        s.pages = pages
        s.prefill_ids = ids
        s.prefill_off = off
        s.out_toks = list(done)
        s.stop_len = stop
        s.eos = int(payload["eos"])
        s.admit_seq = self._admit_counter
        self._admit_counter += 1
        self._bt[b, :] = 0
        self._bt[b, :len(pages)] = pages
        self._stats["admitted"] += 1
        self._stats["pages_allocated"] += len(alloc)
        if matched:
            self._stats["cache_hits"] += 1
            self._stats["cache_hit_tokens"] += len(matched) * ps
        self._mig_stats["migrated_in"] += 1
        self._tl.enqueued(rid, prompt.size, mnt)
        self._tl.admitted(rid, b, cached_tokens=len(matched) * ps,
                          resume_len=off)
        self._tl.migrated(rid, "in", pages=n_imp, phase="prefill")
        self._note_peak()
        return rid

    def discard_request(self, rid) -> bool:
        """Silently relinquish a queued or resident request — the
        SOURCE half of a completed live migration.  No
        CompletedRequest is emitted (the request lives on at the
        destination, whose retirement owns the finish reason); a
        resident's fully-written pages are published to the prefix
        cache first, then the slot funnels through
        :meth:`_release_slot` as always.  Returns ``False`` without
        touching anything when a racing :meth:`cancel` marked the
        slot: the sweep finalizes it as "cancelled" HERE — the caller
        must drop the destination's restore so exactly one side
        honors the cancel.  Raises ``KeyError`` when ``rid`` is not
        in flight."""
        for i, r in enumerate(self._queue):
            if r.rid == rid:
                del self._queue[i]
                self._mig_stats["migrated_out"] += 1
                self._tl.migrated(rid, "out", phase="queued")
                return True
        for b, s in enumerate(self._slots):
            if s.req is not None and s.req.rid == rid:
                if s.cancelled:
                    return False
                phase = s.phase
                n_pages = len(s.pages)
                self._publish_slot(b)
                self._release_slot(b)
                self._mig_stats["migrated_out"] += 1
                self._tl.migrated(rid, "out", pages=n_pages,
                                  phase=phase)
                return True
        raise KeyError(f"request {rid!r} is not queued or resident")

    # ------------------------------------------------- scheduling -----
    def _release_slot(self, b):
        """Free slot ``b``: pages drop their resident reference (the
        prefix cache routes them — ref-0 indexed pages stay CACHED for
        future admissions, the rest return to the free list), the
        block-table row is nulled (null page: a frozen slot's writes
        can never touch a reissued page), the slot reset.  The ONLY
        way pages leave a slot — every retire/finalize/preempt path
        funnels here."""
        s = self._slots[b]
        if self._proposer is not None and s.req is not None:
            # proposer state follows the page discipline: a slot that
            # drops its pages drops its draft KV too (a preempted
            # request's proposer re-prefills on re-admission)
            self._proposer.release(s.req.rid)
        self._cache.release(s.pages)
        self._bt[b, :] = 0
        self._slots[b] = _Slot()

    def _publish_slot(self, b):
        """Index slot ``b``'s fully-written pages in the prefix cache
        (partial tail pages stay private) so later admissions — and
        this request's OWN re-admission after a preemption — map the
        prefix instead of re-prefilling.  Must run before
        :meth:`_release_slot` reads the slot's state away."""
        s = self._slots[b]
        n = s.len_written
        if n < self.page_size:
            return
        if s.phase == "prefill":
            ids = s.prefill_ids[:n]
        else:
            ids = np.concatenate(
                [s.req.prompt,
                 np.asarray(s.out_toks, np.int32)])[:n]
        self._cache.publish(ids, s.pages, n)

    def _finalize_slot(self, b, reason, error=None):
        """Retire slot ``b`` off the normal path (timeout / cancelled /
        failed / preempt-to-nowhere): free its pages, null its block
        table row, emit the partial result."""
        s = self._slots[b]
        toks = np.asarray(s.out_toks[:s.req.max_new_tokens], np.int32)
        comp = CompletedRequest(s.req.rid, s.req.prompt, toks, reason,
                                error)
        if reason != "failed":  # a guard-failed slot's KV is suspect:
            self._publish_slot(b)  # never index poisoned pages
        self._tl.retired(s.req.rid, reason, int(toks.size),
                         s.req.preemptions)
        self._release_slot(b)
        return comp

    def _retire(self):
        out = []
        for b, s in enumerate(self._slots):
            if s.req is None or not s.done or s.cancelled:
                continue  # cancelled-but-done: _sweep finalizes it as
                          # "cancelled" (cancel() already promised so)
            toks = s.out_toks[:s.req.max_new_tokens]
            reason = "length"
            if s.eos >= 0 and s.eos in toks:
                toks = toks[:toks.index(s.eos) + 1]
                reason = "stop"
            out.append(CompletedRequest(
                s.req.rid, s.req.prompt, np.asarray(toks, np.int32),
                reason))
            self._publish_slot(b)
            self._tl.retired(s.req.rid, reason, len(toks),
                             s.req.preemptions)
            self._release_slot(b)
            self._stats["retired"] += 1
        return out

    def _sweep(self, now):
        """Step-boundary policy sweep: expire deadlines (queued AND
        resident) and finalize cancelled residents."""
        out = []
        if any(r.deadline is not None and now >= r.deadline
               for r in self._queue):
            kept = deque()
            for r in self._queue:
                if r.deadline is not None and now >= r.deadline:
                    self._stats["timeouts"] += 1
                    out.append(CompletedRequest(
                        r.rid, r.prompt,
                        np.asarray(r.done_toks, np.int32), "timeout"))
                    self._tl.retired(r.rid, "timeout",
                                     len(r.done_toks), r.preemptions)
                else:
                    kept.append(r)
            self._queue = kept
        for b, s in enumerate(self._slots):
            if s.req is None:
                continue
            if s.cancelled:
                self._stats["cancelled"] += 1
                out.append(self._finalize_slot(b, "cancelled"))
            elif s.req.deadline is not None and now >= s.req.deadline:
                self._stats["timeouts"] += 1
                out.append(self._finalize_slot(b, "timeout"))
        return out

    # --------------------------------------------- page allocation ----
    def _admit_need(self, req):
        """Pages an admission reserves: the (resume) prompt plus ONE
        decode slot — growth is on-demand from there."""
        resume = req.prompt.size + len(req.done_toks)
        stop = req.prompt.size + req.max_new_tokens
        target = max(resume, min(resume + 1, stop))
        return max(1, -(-target // self.page_size))

    def _note_peak(self):
        self._stats["peak_pages_in_use"] = max(
            self._stats["peak_pages_in_use"], self._pages_in_use())

    def _admit(self):
        for b, s in enumerate(self._slots):
            if s.req is not None or not self._queue:
                continue
            req = self._queue[0]
            # a preempted request resumes at prompt + tokens_so_far:
            # greedy decode is deterministic and the ragged prefill and
            # decode paths agree bitwise, so the resumed stream is
            # identical to the uncontended one
            if req.done_toks:
                resume_ids = np.concatenate(
                    [req.prompt, np.asarray(req.done_toks, np.int32)])
            else:
                resume_ids = req.prompt
            resume = int(resume_ids.size)
            stop = req.prompt.size + req.max_new_tokens
            need_total = self._admit_need(req)
            # radix walk: the longest already-indexed prefix rides on
            # its existing pages; prefill starts at the first uncached
            # token.  A FULL (page-aligned) hit still has to compute
            # the last position's logits, so the divergence page is
            # copy-on-write: the shared page's KV is duplicated into a
            # private page and the one recomputed token writes there —
            # shared pages are never write targets.
            matched = self._cache.match(resume_ids)
            cow_src = None
            if matched and len(matched) * self.page_size >= resume:
                cow_src = matched.pop()
            prefill_off = (resume - 1 if cow_src is not None
                           else len(matched) * self.page_size)
            self._cache.retain(matched)   # pin before availability math
            n_alloc = need_total - len(matched)
            if n_alloc > self._cache.available():
                self._cache.release(matched)  # unpin: back to the LRU
                break                 # head-of-line: keep arrival order
            self._queue.popleft()
            alloc = []
            for _ in range(n_alloc):  # cannot dry up: available() holds
                alloc.append(self._cache.acquire(key=str(req.rid)))
            pages = matched + alloc
            s.req = req
            s.phase = "prefill"
            s.pages = pages
            s.prefill_ids = resume_ids
            s.prefill_off = prefill_off
            s.out_toks = list(req.done_toks)
            s.stop_len = stop
            s.eos = req.eos_token_id
            s.admit_seq = self._admit_counter
            self._admit_counter += 1
            self._bt[b, :] = 0
            self._bt[b, :len(pages)] = pages
            if cow_src is not None:
                self._cow_page(cow_src, alloc[0])
            self._stats["admitted"] += 1
            self._stats["pages_allocated"] += len(alloc)
            # demand is counted once per request: preempt resumes and
            # coordinator requeues re-admit the same logical request,
            # and re-counting them would report retry traffic as
            # prefill "savings" (computed stays net of cache restores)
            if not req.requested_counted:
                self._stats["prefill_tokens_requested"] += resume
                req.requested_counted = True
            self._tl.admitted(req.rid, b, cached_tokens=prefill_off,
                              resume_len=resume)
            if prefill_off:
                self._stats["cache_hits"] += 1
                self._stats["cache_hit_tokens"] += prefill_off
        self._note_peak()

    def _pick_victim(self, b):
        """Preemption victim for grower ``b``: the latest-admitted
        resident admitted AFTER ``b`` (never one ahead of it — the
        earliest resident must always win, which is what makes
        preemption converge). None when ``b`` is itself the latest."""
        me = self._slots[b].admit_seq
        victim, vseq = None, me
        for i, s in enumerate(self._slots):
            if i != b and s.req is not None and s.admit_seq > vseq:
                victim, vseq = i, s.admit_seq
        return victim

    def _preempt(self, b):
        """Evict slot ``b``: PUBLISH its fully-written pages into the
        prefix cache (they become ref-0 cached, not freed — LRU-newest,
        so they survive unless the pool is truly starved) and requeue
        the request at the HEAD (it outranks everything queued).
        Re-admission walks the index and restores from its own pages:
        only the tokens past the last full page re-prefill, closing
        the recompute gap of plain preempt-and-requeue."""
        s = self._slots[b]
        req = s.req
        req.done_toks = list(s.out_toks)
        req.preemptions += 1
        self._queue.appendleft(req)
        self._tl.preempted(req.rid, len(s.out_toks))
        self._publish_slot(b)
        self._release_slot(b)
        self._stats["preemptions"] += 1

    def _ensure_tokens(self, b, n_tokens):
        """Grow slot ``b``'s block table to hold ``n_tokens`` resident
        tokens.  Under pool pressure the allocator first EVICTS ref-0
        cached prefix pages (LRU), then preempts later-admitted victims
        (or under the injected ``engine_page_pressure`` drill, which
        forces the preempt path directly). Returns False when ``b``
        itself had to be preempted (it was the latest-admitted and the
        pool is exhausted)."""
        s = self._slots[b]
        need = -(-n_tokens // self.page_size)
        while len(s.pages) < need:
            pg = None
            if not faults.check(SITE_PAGE_PRESSURE, key=str(s.req.rid)):
                pg = self._cache.acquire(key=str(s.req.rid))
            if pg is None:
                victim = self._pick_victim(b)
                if victim is None:
                    self._preempt(b)
                    return False
                self._preempt(victim)
                continue
            self._bt[b, len(s.pages)] = pg
            s.pages.append(pg)
            self._stats["pages_allocated"] += 1
        self._note_peak()
        return True

    def step(self):
        """One scheduling step: retire, sweep policies, admit, grow/
        preempt, dispatch.  Returns the requests completed by the
        PREVIOUS dispatch plus any policy finalizations (retirement
        happens at step boundaries).  A page-accounting violation
        (``CacheIntegrityError``, PDT-E019 — an allocator bug, never a
        user error) dumps a flight record before propagating."""
        try:
            return self._step_inner()
        except CacheIntegrityError as e:
            _flight.dump("cache_integrity", error=e)
            raise

    def _step_inner(self):
        completed = self._retire()
        if self._early:
            completed.extend(self._early)
            self._early.clear()
        now = self._clock()
        completed.extend(self._sweep(now))
        # SLO judgment rides the step boundary (throttled to the
        # evaluation interval — one float compare most steps, never a
        # per-token host sync)
        if self._slo is not None:
            self._slo.maybe_evaluate(now)
        self._admit()
        self._stats["steps"] += 1
        if self.spec_decode and any(
                s.phase in ("prefill", "decode") for s in self._slots):
            # speculative mode: ONE program serves prefill chunks AND
            # verify segments (q_lens up to spec_k+1) — the decode
            # window scan cannot host a Python-side proposer
            self._run_spec()
        elif any(s.phase == "prefill" for s in self._slots):
            self._run_mixed()
        elif any(s.phase == "decode" for s in self._slots):
            self._run_decode()
        elif self._queue:
            # backstop only: with every slot free the full pool is
            # available (cached prefix pages are all evictable once no
            # resident pins them) and eager PageBudgetError already
            # rejected anything that cannot fit it, so this is
            # unreachable for admissible request mixes
            req = self._queue[0]
            err = RuntimeError(
                f"request {req.rid} needs {self._admit_need(req)} pages "
                f"but the pool only has {self.total_pages - 1}; raise "
                "total_pages or lower max_new_tokens")
            _flight.dump("pool_backstop", error=err,
                         extra={"rid": req.rid})
            raise err
        return completed

    def _fail(self, b):
        """Decode guard hit: fail ONE request with the coded error; the
        engine and every co-resident request keep going.  The flight
        recorder dumps the recent event ring — the failed request's
        admission/prefill/decode timeline included — so the postmortem
        starts with context, not a bare error string."""
        s = self._slots[b]
        rid = s.req.rid
        err = DecodeGuard.failure(rid, s.len_written)
        self._stats["failed"] += 1
        self._early.append(self._finalize_slot(b, "failed", err))
        _flight.dump("nan_decode", error=err,
                     extra={"rid": rid, "slot": b})

    def _dispatch(self, kind, fn):
        def _on_retry(_exc, _attempt):
            self._stats["retries"] += 1
        # dispatch_retries counts RETRIES (re-attempts after a
        # transient), so N=0 disables retry and N=1 absorbs one fault.
        # Each dispatch runs under a serving.dispatch tracing span
        # (ISSUE 12): the span begin/end pair lands in the event ring
        # for export_trace, and the serving.dispatch timeline event
        # emitted INSIDE the span inherits its trace/parent ids — so a
        # trace carried in over rpc (disaggregated prefill/decode
        # handoff) threads through to the dispatch that served it.
        # With watchdog_ms > 0 the dispatch is also watchdog-armed
        # (ISSUE 14): past the deadline the stall thread's stacks and
        # the flight record are captured and EngineStallError is
        # injected here.  A truly stalled call never ran to
        # completion, so slot state is untouched and the next step()
        # re-plans the same dispatch bitwise.  A dispatch that
        # COMPLETES just past the deadline is the race case: its
        # donated buffers are already consumed, so discarding the
        # result would strand the engine — the completion cell below
        # records the result the instant fn() returns, a late
        # injection is swallowed and the real result used (the
        # residual few-bytecode window before the cell append can
        # still lose a result; the donated-buffer guards then fail
        # the NEXT dispatch loudly rather than corrupting state).
        timed = _obs_metrics.enabled()
        token = _watchdog.arm("serving.dispatch", self.watchdog_ms,
                              key=str(kind),
                              interrupt_exc=EngineStallError)
        done_cell = []

        def _fn_completing():
            out = fn()
            done_cell.append(out)
            return out

        try:
            try:
                with _tracing.span("serving.dispatch", op=str(kind)):
                    t0 = time.perf_counter() if timed else 0.0
                    res = dispatch_retry(
                        kind, _fn_completing,
                        max_attempts=self.dispatch_retries + 1,
                        on_retry=_on_retry)
                    token.disarm()   # close the injection window now —
                    # timeline/span bookkeeping must not be chargeable
                    if timed:
                        self._tl.dispatch(
                            kind, (time.perf_counter() - t0) * 1e3)
            except EngineStallError as e:
                if done_cell:
                    # late injection: the program ran; the result is
                    # real and its inputs are gone — keep it
                    res = done_cell[-1]
                else:
                    where = (f"; flight record at {token.dump_path}"
                             if token.dump_path else "")
                    raise EngineStallError(
                        f"engine dispatch {kind!r} stalled past the "
                        f"{self.watchdog_ms:g} ms watchdog deadline — "
                        f"thread stacks and the request timeline are "
                        f"in the flight record{where} "
                        f"[{EngineStallError.error_code}]") from e
        finally:
            token.disarm()
        return res

    # compiled serving programs cache ON the model (generate()'s
    # _decode_step_cache idiom): engines with the same bucket geometry
    # — page/table/pool shapes, token budget, slot count — share the
    # compiled mixed/decode programs instead of re-tracing
    def _program_cache(self):
        return self.model.__dict__.setdefault("_serving_step_cache", {})

    def _geometry(self):
        tp_key = None
        if self._tpp is not None:
            tp_key = (self.tp_axis,
                      tuple(d.id for d in self._jmesh.devices.flat))
        return (self.max_slots, self.page_size, self.np_per_seq,
                self.total_pages, self.token_budget, self.q_block,
                self.pages_per_block, self.kv_quant, self.megakernel,
                tp_key)

    def _audit_program(self, name, fn, args, donated=()):
        """Whole-program audit (analysis/program.py) of a raw-jitted
        serving program: collective schedule, donation/HBM, recompile
        risk. Once per (program, geometry) — the audit runs at the
        dispatch that first compiles the program and never again, so
        steady-state dispatches do zero analysis work. The to_static
        programs (mixed/decode steps) are audited by the jit capture
        itself; this covers the ``jax.jit`` sites that bypass it."""
        from .. import analysis as _analysis
        if _analysis.mode() == "off":
            return
        done = self.model.__dict__.setdefault("_serving_audit_done",
                                              set())
        key = (name,) + self._geometry()
        if key in done:
            return
        done.add(key)
        _analysis.audit_jitted(fn, args, where=f"engine.{name}",
                               donated=donated)

    # ------------------------------------------- copy-on-write --------
    def _get_cow_fn(self):
        if self._cow_fn is None:
            key = ("cow", len(self._caches)) + self._geometry()
            cache = self._program_cache()
            self._cow_fn = cache.get(key)
            if self._cow_fn is None:
                n = len(self._caches)

                def cow(src, dst, *pools):
                    return tuple(p.at[:, dst].set(p[:, src])
                                 for p in pools)

                self._cow_fn = jax.jit(
                    cow, donate_argnums=tuple(range(2, 2 + n)))
                cache[key] = self._cow_fn
        return self._cow_fn

    def _cow_page(self, src, dst):
        """Copy-on-write at the divergence page: duplicate shared page
        ``src``'s KV (every layer pool) into private page ``dst`` in
        ONE donated-buffer dispatch — src/dst are traced scalars, so
        every COW event reuses the same compiled program.  The copied
        bits are exactly what this request's own prefill would have
        written, so the recompute that follows stays bitwise."""
        fn = self._get_cow_fn()
        vals = [c._read() for c in self._caches]
        self._audit_program(
            "cow", fn,
            (jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
             *vals),
            donated=tuple(range(2, 2 + len(vals))))

        def _cow_call():
            # donated inputs: only retry while they are still alive
            # (same contract as the decode-window dispatch)
            if any(getattr(v, "is_deleted", lambda: False)()
                   for v in vals):
                raise RuntimeError(
                    "cow dispatch failed after its KV buffers were "
                    "donated; a mid-execution transient is "
                    "unrecoverable at this layer — re-create the "
                    "engine and re-submit the pending requests")
            return fn(jnp.asarray(src, jnp.int32),
                      jnp.asarray(dst, jnp.int32), *vals)

        new = self._dispatch("cow", _cow_call)
        for t, v in zip(self._caches, new):
            t._data = v
            t._node = None

    # ---------------------------------------------- TP adapters -------
    # the TP programs (models/generation.py make_tp_*) are plain jitted
    # shard_map functions over (data vectors, *sharded params, *cache
    # pools); these adapters give them the SAME call surface as the
    # to_static-compiled single-device programs — Tensors in, Tensors
    # out — so _run_mixed/_run_spec need no TP branch of their own
    def _tp_wrap(self, jitted, name="tp"):
        tpp = self._tpp
        n_caches = len(self._caches)

        def call(*args):
            vals = [a._read() for a in args]
            n_data = len(vals) - n_caches
            full = (*vals[:n_data], *tpp.vals, *vals[n_data:])
            self._audit_program(name, jitted, full)
            outs = jitted(*full)
            return tuple(Tensor(o) for o in outs)

        return call

    # ------------------------------------------------- mixed step -----
    def _get_mixed_fn(self):
        if self._mixed_fn is None:
            key = ("mixed", "guard") + self._geometry()
            cache = self._program_cache()
            self._mixed_fn = cache.get(key)
        if self._mixed_fn is None and self._tpp is not None:
            from ..models.generation import make_tp_mixed
            self._mixed_fn = self._tp_wrap(make_tp_mixed(
                self.model, self._tpp, self._jmesh, self.q_block,
                self.pages_per_block, len(self._caches)),
                name="tp_mixed")
            self._program_cache()[("mixed", "guard")
                                  + self._geometry()] = self._mixed_fn
        if self._mixed_fn is None:
            from .. import jit as jit_mod
            from .. import ops
            from ..models.generation import guarded_argmax
            model, ragged, qb = self.model, self._ragged, self.q_block
            ppb = self.pages_per_block

            def mixed(ids_t, tok_pos, tok_slot, tok_valid, kv_lens,
                      q_lens, last_idx, poison, bt, *cs):
                import paddle_tpu as pp
                with pp.no_grad():
                    logits, new = ragged(model, ids_t, tok_pos, tok_slot,
                                         tok_valid, kv_lens, q_lens, bt,
                                         list(cs), qb, ppb)
                    lg = ops.gather(logits, last_idx)       # [B, V]
                    nxt, bad = guarded_argmax(lg, poison)
                return (nxt, bad) + tuple(new)

            self._mixed_fn = jit_mod.to_static(mixed)
            cache[key] = self._mixed_fn
        return self._mixed_fn

    # shared segment planning/packing for the mixed AND speculative
    # dispatch paths — ONE implementation, so spec-on scheduling can
    # never drift from spec-off (the subsystem's bitwise-parity claim
    # rests on both paths planning prefill, growing pages and packing
    # tokens identically; only the decode-segment contents differ)
    def _plan_prefill(self, plan, budget):
        """Add each prefill slot's next chunk that fits ``budget`` to
        ``plan`` (entries ``(segment, pos0, take, drafts)``)."""
        qb = self.q_block
        for b, s in enumerate(self._slots):
            if s.phase != "prefill":
                continue
            rem = s.prefill_ids.size - s.prefill_off
            take = min(rem, budget)
            while take > 0 and -(-take // qb) * qb > budget:
                take -= 1     # q_block padding must fit the budget
            if take <= 0:
                continue      # budget exhausted: sits out this step
            budget -= -(-take // qb) * qb
            plan[b] = (list(s.prefill_ids[s.prefill_off:
                                          s.prefill_off + take]),
                       s.prefill_off, take, None)

    def _grow_plan(self, plan):
        """Page growth in admission order (earliest first — it can
        always win); growth may preempt later-admitted slots, planned
        or not, so drop plans whose slot got evicted or that
        self-preempted (latest + dry pool)."""
        order = sorted(plan, key=lambda b: self._slots[b].admit_seq)
        for b in order:
            s = self._slots[b]
            if s.req is None:           # evicted by an earlier grower
                plan.pop(b)
                continue
            seg, pos0, _take, _d = plan[b]
            if not self._ensure_tokens(b, pos0 + len(seg)):
                plan.pop(b)
        for b in list(plan):
            if self._slots[b].req is None:
                plan.pop(b)

    def _pack_plan(self, plan):
        """Pack the plan's segments into the token-budget vectors;
        returns ``(tok, tpos, tslot, tvalid, kv_lens, q_lens,
        last_idx, row0)`` with each segment starting at a q_block
        edge.  Also meters prefill compute (stats + timeline)."""
        qb, T, B = self.q_block, self.token_budget, self.max_slots
        tok = np.zeros(T, np.int32)
        tpos = np.zeros(T, np.int32)
        tslot = np.zeros(T, np.int32)
        tvalid = np.zeros(T, np.int32)
        kv_lens = np.ones(B, np.int32)
        q_lens = np.zeros(B, np.int32)
        last_idx = np.zeros(B, np.int32)
        row0 = {}
        cur = 0
        for b in range(B):
            if b not in plan:
                continue
            s = self._slots[b]
            seg, pos0, take, _d = plan[b]
            n = len(seg)
            tok[cur:cur + n] = seg
            tpos[cur:cur + n] = pos0 + np.arange(n)
            tslot[cur:cur + n] = b
            tvalid[cur:cur + n] = 1
            q_lens[b] = n
            kv_lens[b] = s.len_written + n
            last_idx[b] = cur + n - 1
            row0[b] = cur
            cur += -(-n // qb) * qb   # next segment at a q_block edge
            if take is not None:      # honest prefill-compute meter:
                self._stats["prefill_tokens_computed"] += take
                self._tl.prefill_chunk(s.req.rid, b, take, pos0)
        return (tok, tpos, tslot, tvalid, kv_lens, q_lens, last_idx,
                row0)

    def _run_mixed(self):
        """Pack one q_block-aligned segment per active slot — decode
        slots their current token, prefill slots the next chunk that
        fits — grow/preempt for the pages this step will write, and
        advance everything in ONE dispatch."""
        qb, T, B = self.q_block, self.token_budget, self.max_slots
        plan = {}      # b -> (segment, pos0, prefill take|None, drafts)
        budget = T
        for b, s in enumerate(self._slots):
            if s.phase == "decode":
                plan[b] = ([int(s.cur_tok)], s.cur_pos, None, None)
                budget -= qb
        self._plan_prefill(plan, budget)
        self._grow_plan(plan)
        if not plan:
            return
        (tok, tpos, tslot, tvalid, kv_lens, q_lens, last_idx,
         _row0) = self._pack_plan(plan)
        poison = self._guard.poison(
            [self._slots[b].req.rid if b in plan else None
             for b in range(B)])
        fn = self._get_mixed_fn()
        args = [Tensor(jnp.asarray(tok[None, :])),
                Tensor(jnp.asarray(tpos)), Tensor(jnp.asarray(tslot)),
                Tensor(jnp.asarray(tvalid)),
                Tensor(jnp.asarray(kv_lens)),
                Tensor(jnp.asarray(q_lens)),
                Tensor(jnp.asarray(last_idx)),
                Tensor(jnp.asarray(poison)),
                Tensor(jnp.asarray(self._bt))]
        res = self._dispatch("mixed", lambda: fn(*args, *self._caches))
        nxt = np.asarray(res[0]._read()).reshape(-1)
        bad = np.asarray(res[1]._read()).reshape(-1)
        self._caches = list(res[2:])
        self._stats["mixed_steps"] += 1
        self._stats["decode_dispatches"] += 1
        for b in sorted(plan):
            s = self._slots[b]
            _seg, _pos0, take, _d = plan[b]
            if bad[b]:
                self._fail(b)
                continue
            if take is None:
                self._accept(s, int(nxt[b]))
            else:
                s.prefill_off += take
                if s.prefill_off >= s.prefill_ids.size:
                    s.phase = "decode"
                    s.cur_pos = s.prefill_ids.size
                    s.cur_tok = int(nxt[b])
                    s.out_toks.append(int(nxt[b]))
                    self._stats["tokens_generated"] += 1
                    self._tl.token(s.req.rid)

    def _accept(self, s, t):
        s.out_toks.append(t)
        s.cur_tok = t
        s.cur_pos += 1
        self._stats["tokens_generated"] += 1
        self._tl.token(s.req.rid)

    # --------------------------------------- speculative verify -------
    def _get_spec_fn(self):
        need_lg = self.spec_temperature > 0
        key = ("spec", "guard", need_lg) + self._geometry()
        cache = self._program_cache()
        if self._spec_fn is None:
            self._spec_fn = cache.get(key)
        if self._spec_fn is None and self._tpp is not None:
            from ..models.generation import make_tp_spec
            self._spec_fn = self._tp_wrap(make_tp_spec(
                self.model, self._tpp, self._jmesh, self.q_block,
                self.pages_per_block, len(self._caches), need_lg),
                name="tp_spec")
            cache[key] = self._spec_fn
        if self._spec_fn is None:
            from .. import jit as jit_mod
            from .. import ops
            from ..models.generation import verify_argmax
            model, ragged, qb = self.model, self._ragged, self.q_block
            ppb = self.pages_per_block

            if need_lg:
                # sampling mode returns per-slot logits ROWS gathered
                # in-graph ([B*(spec_k+1), V] — never the whole
                # [token_budget, V] block, whose prefill/padding rows
                # the host would not read)
                def spec(ids_t, tok_pos, tok_slot, tok_valid, kv_lens,
                         q_lens, poison, gather_idx, bt, *cs):
                    import paddle_tpu as pp
                    with pp.no_grad():
                        logits, new = ragged(
                            model, ids_t, tok_pos, tok_slot, tok_valid,
                            kv_lens, q_lens, bt, list(cs), qb, ppb)
                        toks, bad = verify_argmax(logits, tok_slot,
                                                  tok_valid, poison)
                        lgs = ops.gather(logits, gather_idx)
                    return (toks, bad, lgs) + tuple(new)
            else:
                def spec(ids_t, tok_pos, tok_slot, tok_valid, kv_lens,
                         q_lens, poison, bt, *cs):
                    import paddle_tpu as pp
                    with pp.no_grad():
                        logits, new = ragged(
                            model, ids_t, tok_pos, tok_slot, tok_valid,
                            kv_lens, q_lens, bt, list(cs), qb, ppb)
                        toks, bad = verify_argmax(logits, tok_slot,
                                                  tok_valid, poison)
                    return (toks, bad) + tuple(new)

            self._spec_fn = jit_mod.to_static(spec)
            cache[key] = self._spec_fn
        return self._spec_fn

    def _run_spec(self):
        """Speculative mixed step (ISSUE 9): prefill slots pack chunks
        exactly like :meth:`_run_mixed`; decode slots pack their
        current token plus up to ``spec_k`` proposed tokens as a
        ragged VERIFY segment (``q_lens = K+1`` — per-sequence lengths
        are DATA to the kernel, so this is the same compiled program
        every step) and advance by the accepted length.  Retirement is
        RAGGED: each slot's ``cur_pos``/``len_written`` moves by its
        own accept count, and KV written past the first rejection is
        rolled back positionally — ``kv_lens`` masks it and the next
        dispatch overwrites the same (page, slot) bytes, so published
        prefix pages only ever hold accepted tokens."""
        qb, T, B = self.q_block, self.token_budget, self.max_slots
        plan = {}   # b -> (segment, pos0, prefill take|None, drafts)
        budget = T
        for b, s in enumerate(self._slots):
            if s.phase != "decode":
                continue
            # room: at most stop_len - cur_pos - 1 tokens may still be
            # emitted and one verify emits up to K+1, so K is clamped
            # to keep every written position inside the page table
            k = min(self.spec_k, max(s.stop_len - s.cur_pos - 2, 0))
            drafts = np.empty(0, np.int32)
            if k > 0:
                ids = np.concatenate(
                    [s.req.prompt, np.asarray(s.out_toks, np.int32)])
                drafts = np.asarray(
                    self._proposer.propose(s.req.rid, ids, k),
                    np.int32).reshape(-1)[:k]
                if drafts.size and faults.check(
                        SITE_DRAFT_MISMATCH, key=str(s.req.rid)):
                    # drill: corrupt the proposal so this verify step
                    # rejects it — outputs must stay bitwise, only the
                    # accept rate moves
                    drafts = ((drafts + 1)
                              % self.model.cfg.vocab_size).astype(
                                  np.int32)
            seg = [int(s.cur_tok)] + [int(t) for t in drafts]
            plan[b] = (seg, s.cur_pos, None, drafts)
            budget -= -(-len(seg) // qb) * qb
        self._plan_prefill(plan, budget)
        self._grow_plan(plan)
        if not plan:
            return
        (tok, tpos, tslot, tvalid, kv_lens, q_lens, _last_idx,
         row0) = self._pack_plan(plan)
        # the standing nan drill arms on every dispatch a slot rides;
        # engine_draft_nan arms ONLY on slots with a verify segment
        # this dispatch (the site's documented scope)
        poison = self._guard.poison(
            [self._slots[b].req.rid if b in plan else None
             for b in range(B)])
        poison = poison + self._guard.poison(
            [self._slots[b].req.rid
             if b in plan and plan[b][2] is None else None
             for b in range(B)], sites=(SITE_DRAFT_NAN,))
        need_lg = self.spec_temperature > 0
        W = self.spec_k + 1            # gathered rows per slot
        fn = self._get_spec_fn()
        args = [Tensor(jnp.asarray(tok[None, :])),
                Tensor(jnp.asarray(tpos)), Tensor(jnp.asarray(tslot)),
                Tensor(jnp.asarray(tvalid)),
                Tensor(jnp.asarray(kv_lens)),
                Tensor(jnp.asarray(q_lens)),
                Tensor(jnp.asarray(poison))]
        if need_lg:
            # sampling needs logits rows: slot b's W-row window holds
            # its verify rows (padded by repetition) — or, for a
            # prefill slot, its LAST chunk row at window position 0
            # (the first-token sample when the chunk completes prefill)
            gather_idx = np.zeros(B * W, np.int32)
            for b, (seg, _pos0, take, _d) in plan.items():
                if take is None:
                    n = len(seg)
                    idx = row0[b] + np.minimum(np.arange(W), n - 1)
                else:
                    idx = np.full(W, row0[b] + take - 1)
                gather_idx[b * W:(b + 1) * W] = idx
            args.append(Tensor(jnp.asarray(gather_idx)))
        args.append(Tensor(jnp.asarray(self._bt)))
        res = self._dispatch("verify",
                             lambda: fn(*args, *self._caches))
        toks = np.asarray(res[0]._read()).reshape(-1)
        bad = np.asarray(res[1]._read()).reshape(-1)
        n_head = 2
        logits = None
        if need_lg:
            logits = np.asarray(res[2]._read()).astype(
                np.float32).reshape(B * W, -1)
            n_head = 3
        self._caches = list(res[n_head:])
        self._stats["decode_dispatches"] += 1
        if any(p[2] is not None for p in plan.values()):
            self._stats["mixed_steps"] += 1
        for b in sorted(plan):
            s = self._slots[b]
            seg, pos0, take, drafts = plan[b]
            if bad[b]:
                self._fail(b)        # per-draft guard: this slot alone
                continue
            if take is not None:     # prefill chunk — as _run_mixed,
                s.prefill_off += take       # except a sampling engine
                if s.prefill_off >= s.prefill_ids.size:  # SAMPLES the
                    if need_lg:                     # first token too
                        nxt = self._sample_row(logits[b * W])
                    else:
                        nxt = int(toks[row0[b] + take - 1])
                    s.phase = "decode"
                    s.cur_pos = s.prefill_ids.size
                    s.cur_tok = nxt
                    s.out_toks.append(nxt)
                    self._stats["tokens_generated"] += 1
                    self._tl.token(s.req.rid)
                continue
            # verify: greedy accepts the longest agreed draft prefix
            # plus the target's free next token; spec_temperature > 0
            # switches to the sampling rule over the gathered logits
            n = len(seg)
            if need_lg:
                emitted, m = _spec.accept_sampled(
                    drafts, logits[b * W:b * W + n],
                    self.spec_temperature, self._spec_rng,
                    rejection_sampling=self.spec_rejection_sampling)
            else:
                emitted, m = _spec.accept_greedy(
                    drafts, toks[row0[b]:row0[b] + n])
            self._spec_stats["spec_proposed"] += int(drafts.size)
            self._spec_stats["spec_accepted"] += int(m)
            adv = 0
            for t in emitted:
                self._accept(s, int(t))
                adv += 1
                if (s.eos >= 0 and int(t) == s.eos) \
                        or s.cur_pos + 1 >= s.stop_len:
                    break            # host replay of the stop rule
            self._tl.verify_window(s.req.rid, int(drafts.size),
                                   int(m), adv)

    def _sample_row(self, row):
        """Sample one token from a single logits row at the engine's
        speculative temperature (the prefill-completion token of a
        sampling-mode engine — argmax here would leak a greedy token
        into an otherwise exactly-sampled stream).  Routes through
        ``accept_sampled``'s free-token path so the sampling rule has
        ONE home and cannot drift."""
        emitted, _ = _spec.accept_sampled(
            np.empty(0, np.int32), row[None], self.spec_temperature,
            self._spec_rng)
        return int(emitted[0])

    # ------------------------------------------------ decode window ---
    def _get_step_fn(self):
        if self._step_fn is None:
            key = ("decode",) + self._geometry()
            cache = self._program_cache()
            self._step_fn = cache.get(key)
        if self._step_fn is None:
            from .. import jit as jit_mod
            from ..models.generation import paged_slot_attention
            model, decode = self.model, self._decode
            ppb = self.pages_per_block

            if self.megakernel:
                # decode megakernel (ISSUE 18): ~3 fused Pallas
                # dispatches per layer plus the fused sampling
                # epilogue — the step returns the guarded greedy pick
                # alongside the logits, so windows and the bootstrap
                # never re-derive it
                from ..models.generation import _decode_fused_fn
                decode_fused = _decode_fused_fn(model)

                def step(tok, pos, bt, poison, *cs):
                    import paddle_tpu as pp
                    with pp.no_grad():
                        logits, nxt, bad, new = decode_fused(
                            model, tok, pos, bt, list(cs), poison,
                            pages_per_block=ppb)
                    return (logits, nxt, bad) + tuple(new)
            else:
                def step(tok, pos, bt, *cs):
                    import paddle_tpu as pp
                    with pp.no_grad():
                        def attend(q, k, v, kc, vc, p, ks=None,
                                   vs=None):
                            return paged_slot_attention(
                                q, k, v, kc, vc, p, bt,
                                pages_per_block=ppb, k_scales=ks,
                                v_scales=vs)
                        logits, new = decode(model, tok, pos,
                                             list(cs), attend=attend)
                    return (logits,) + tuple(new)

            self._step_fn = jit_mod.to_static(step)
            self._program_cache()[key] = self._step_fn
        return self._step_fn

    def _slot_vectors(self):
        B = self.max_slots
        tok = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        fin = np.ones(B, bool)
        eos = np.full(B, -1, np.int32)
        stop = np.ones(B, np.int32)
        rids = [None] * B
        for b, s in enumerate(self._slots):
            if s.phase != "decode":
                continue
            tok[b, 0] = s.cur_tok
            pos[b] = s.cur_pos
            fin[b] = s.done
            eos[b] = s.eos
            stop[b] = s.stop_len
            rids[b] = s.req.rid
        return tok, pos, fin, eos, stop, rids

    def _grow_decode_slots(self):
        """Reserve the pages the next decode dispatch can write: up to
        ``decode_window`` tokens per live slot (capped at stop_len),
        preempting under pressure. Earliest-admitted first."""
        order = sorted(
            (b for b, s in enumerate(self._slots)
             if s.phase == "decode"),
            key=lambda b: self._slots[b].admit_seq)
        for b in order:
            s = self._slots[b]
            if s.req is None:           # evicted by an earlier grower
                continue
            target = min(s.cur_pos + self.decode_window, s.stop_len)
            self._ensure_tokens(b, max(target, s.cur_pos + 1))

    def _run_decode(self):
        self._grow_decode_slots()
        if not any(s.phase == "decode" for s in self._slots):
            return                      # everyone got preempted
        tok, pos, fin, eos, stop, rids = self._slot_vectors()
        if self._tpp is not None:
            # TP path: the scanned window program is self-contained
            # (explicit sharded params, no captured executable state),
            # so there is no first-scalar-dispatch bootstrap — every
            # decode dispatch is a window.  Token streams are identical
            # either way: the host replay of the stop rule is shared.
            self._run_tp_window(tok, pos, fin, eos, stop, rids)
            return
        step_fn = self._get_step_fn()
        if self._decode_exe is None:
            # a model-cache hit may hand us an already-compiled step
            wrapped = (step_fn if hasattr(step_fn, "_cache")
                       else getattr(step_fn, "__wrapped__", None))
            if wrapped is not None and getattr(wrapped, "_cache", None):
                self._decode_exe = next(iter(wrapped._cache.values()))
        if self._decode_exe is None:
            # first decode dispatch compiles the scalar step; its logits
            # advance every live slot by one token (host argmax; the
            # guard check runs host-side on the same poisoned values
            # the windowed path applies in-graph).  The megakernel step
            # takes the poison lane as an input and returns the guarded
            # pick from its fused sampling epilogue — same bytes, same
            # tie-breaking (first max index), zero host argmax.
            if self.megakernel:
                poison = self._guard.poison(rids)
                res = self._dispatch("decode", lambda: step_fn(
                    Tensor(jnp.asarray(tok)), Tensor(jnp.asarray(pos)),
                    Tensor(jnp.asarray(self._bt)),
                    Tensor(jnp.asarray(poison)), *self._caches))
                nxt = np.asarray(res[1]._read()).astype(np.int32)
                bad = np.asarray(res[2]._read()).astype(bool)
                self._caches = list(res[3:])
            else:
                res = self._dispatch("decode", lambda: step_fn(
                    Tensor(jnp.asarray(tok)), Tensor(jnp.asarray(pos)),
                    Tensor(jnp.asarray(self._bt)), *self._caches))
                lg = np.asarray(res[0]._read()).astype(np.float32)
                self._caches = list(res[1:])
                lg = lg + self._guard.poison(rids)[:, None]
                bad = ~np.isfinite(lg).all(-1)
                nxt = np.where(bad, 0, lg.argmax(-1)).astype(np.int32)
            self._stats["decode_dispatches"] += 1
            accepted = 0
            for b, s in enumerate(self._slots):
                if fin[b]:
                    continue
                if bad[b]:
                    self._fail(b)
                    continue
                self._accept(s, int(nxt[b]))
                accepted += 1
            self._tl.decode_window(accepted, int((~fin).sum()))
            wrapped = (step_fn if hasattr(step_fn, "_cache")
                       else getattr(step_fn, "__wrapped__", None))
            if wrapped is not None and getattr(wrapped, "_cache", None):
                self._decode_exe = next(iter(wrapped._cache.values()))
            return
        self._run_window(tok, pos, fin, eos, stop, rids)

    def _get_window_runner(self, K):
        # cached on the executable (generate()'s idiom) so engines
        # sharing a compiled step also share its window programs
        runners = self._decode_exe.__dict__.setdefault(
            "_slot_window_cache", {})
        runner = runners.get(K)
        if runner is None:
            make = (_make_slot_window_mk if self.megakernel
                    else _make_slot_window)
            runner = make(self._decode_exe, K)
            runners[K] = runner
        return runner

    def _run_window(self, tok, pos, fin, eos, stop, rids):
        """K scanned decode steps in one dispatch; slot state rides the
        scan carry (models/generation.py's window machinery, per-slot).
        The guard's bad flag is part of the carry: a slot that goes
        non-finite freezes in-graph and is failed host-side."""
        exe = self._decode_exe
        K = self.decode_window
        for sync in exe.discovery.host_syncs:
            sync()
        capt = exe.capt_state
        carry_idx, const_idx = exe.state_split()
        cache_vals = [c._read() for c in self._caches]
        cstate = [capt[i]._read() for i in carry_idx]
        const_state = [capt[i]._read() for i in const_idx]
        poison = self._guard.poison(rids)
        runner = self._get_window_runner(K)
        self._audit_program(
            ("window", K), runner,
            (jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(fin),
             jnp.asarray(np.zeros(self.max_slots, bool)),
             jnp.asarray(eos), jnp.asarray(stop), jnp.asarray(poison),
             jnp.asarray(self._bt), cache_vals, cstate, const_state))
        donated = cache_vals + cstate    # runner donate_argnums=(8, 9)

        def _window_call():
            # retry can only re-run this closure while its donated
            # inputs are still alive (a transient raised BEFORE the
            # program consumed them — the engine_dispatch drill, a
            # submit-side connection error). Past donation the buffers
            # are gone: surface that clearly instead of retrying into
            # a confusing deleted-buffer error.
            if any(getattr(v, "is_deleted", lambda: False)()
                   for v in donated):
                raise RuntimeError(
                    "decode-window dispatch failed after its KV/state "
                    "buffers were donated; a mid-execution transient "
                    "is unrecoverable at this layer — re-create the "
                    "engine and re-submit the pending requests")
            return runner(
                jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(fin),
                jnp.asarray(np.zeros(self.max_slots, bool)),
                jnp.asarray(eos), jnp.asarray(stop),
                jnp.asarray(poison), jnp.asarray(self._bt),
                cache_vals, cstate, const_state)

        toks, bads, tokf, posf, finf, badf, cache_vals, cstate = \
            self._dispatch("window", _window_call)
        for i, v in zip(carry_idx, cstate):
            capt[i]._data = v
            capt[i]._node = None
        for t, v in zip(self._caches, cache_vals):
            t._data = v
            t._node = None
        self._stats["decode_dispatches"] += 1
        self._apply_window(np.asarray(toks), np.asarray(bads), fin, K)

    def _apply_window(self, toks, bads, fin, K):
        """Host replay of the device stop rule over one decode
        window's stacked tokens [K, B] / cumulative bad flags [K, B]
        (identical predicate, so the accepted prefix matches the
        carried fin exactly); the first bad step fails the slot and
        discards its frozen tail.  Shared by the single-device and TP
        window paths — the bitwise claim between them rests on this
        being ONE implementation."""
        live = accepted = 0
        for b, s in enumerate(self._slots):
            if s.phase != "decode" or fin[b]:
                continue
            live += 1
            for k in range(K):
                if bads[k, b]:
                    self._fail(b)
                    break
                t = int(toks[k, b])
                self._accept(s, t)
                accepted += 1
                if (s.eos >= 0 and t == s.eos) \
                        or s.cur_pos + 1 >= s.stop_len:
                    break
        self._tl.decode_window(accepted, live)

    def _get_tp_window(self, K):
        key = ("tpwin", K) + self._geometry()
        cache = self._program_cache()
        runner = cache.get(key)
        if runner is None:
            from ..models.generation import make_tp_window
            runner = make_tp_window(self.model, self._tpp, self._jmesh,
                                    self.pages_per_block,
                                    len(self._caches), K,
                                    megakernel=self.megakernel)
            cache[key] = runner
        return runner

    def _run_tp_window(self, tok, pos, fin, eos, stop, rids):
        """K scanned TP decode steps in one dispatch — the sharded
        analog of :meth:`_run_window` (same carry discipline, same
        donated-cache retry contract, same host replay)."""
        K = self.decode_window
        runner = self._get_tp_window(K)
        cache_vals = [c._read() for c in self._caches]
        poison = self._guard.poison(rids)
        self._audit_program(
            ("tpwin", K), runner,
            (jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(fin),
             jnp.asarray(np.zeros(self.max_slots, bool)),
             jnp.asarray(eos), jnp.asarray(stop), jnp.asarray(poison),
             jnp.asarray(self._bt), *self._tpp.vals, *cache_vals))

        def _window_call():
            if any(getattr(v, "is_deleted", lambda: False)()
                   for v in cache_vals):
                raise RuntimeError(
                    "decode-window dispatch failed after its KV "
                    "buffers were donated; a mid-execution transient "
                    "is unrecoverable at this layer — re-create the "
                    "engine and re-submit the pending requests")
            return runner(
                jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(fin),
                jnp.asarray(np.zeros(self.max_slots, bool)),
                jnp.asarray(eos), jnp.asarray(stop),
                jnp.asarray(poison), jnp.asarray(self._bt),
                *self._tpp.vals, *cache_vals)

        res = self._dispatch("window", _window_call)
        toks, bads = res[0], res[1]
        for t, v in zip(self._caches, res[6:]):
            t._data = v
            t._node = None
        self._stats["decode_dispatches"] += 1
        self._apply_window(np.asarray(toks), np.asarray(bads), fin, K)


def _make_slot_window(exe, K):
    """Scan K per-slot greedy decode steps into ONE jitted dispatch.
    The carry holds (token, position, finished, guard-bad) PER SLOT
    plus caches and mutated captured state; finished OR guard-failed
    slots freeze (position and token stop advancing, so their page
    writes keep landing on already owned — or null — pages). The
    stacked per-step bad flags come back so the host can locate the
    first poisoned step exactly."""
    from jax import lax

    from ..models.generation import guarded_argmax

    pure = exe._pure
    n_ret = exe.n_ret
    n_caches = n_ret - 1
    capt = exe.capt_state
    carry_idx, const_idx = exe.state_split()

    def window(tok, pos, fin, bad, eos_ids, stop_lens, poison, bt,
               caches, cstate, const_state):
        def body(c, _):
            tok, pos, fin, bad, caches, cstate = c
            state = [None] * len(capt)
            for i, v in zip(carry_idx, cstate):
                state[i] = v
            for i, v in zip(const_idx, const_state):
                state[i] = v
            outs = pure(tok, pos, bt, *caches, *state)
            lg = outs[0].astype(jnp.float32)
            new_caches = list(outs[1:1 + n_caches])
            new_cstate = list(outs[1 + n_caches:
                                   1 + n_caches + len(carry_idx)])
            nxt_raw, row_bad = guarded_argmax.raw(lg, poison)     # [B]
            bad2 = bad | (row_bad & jnp.logical_not(fin))
            adv = jnp.logical_not(fin | bad2)
            nxt = jnp.where(adv, nxt_raw, tok[:, 0])
            pos2 = jnp.where(adv, pos + 1, pos)
            fin2 = fin | bad2 | ((eos_ids >= 0) & (nxt == eos_ids)) \
                | (pos2 + 1 >= stop_lens)
            return (nxt[:, None], pos2, fin2, bad2, new_caches,
                    new_cstate), (nxt, bad2)

        (tok, pos, fin, bad, caches, cstate), (toks, bads) = lax.scan(
            body, (tok, pos, fin, bad, caches, cstate), None, length=K)
        return toks, bads, tok, pos, fin, bad, caches, cstate

    return jax.jit(window, donate_argnums=(8, 9))


def _make_slot_window_mk(exe, K):
    """Megakernel variant of :func:`_make_slot_window` (ISSUE 18): the
    compiled step already returns ``(logits, nxt, bad, *caches)`` with
    the guarded greedy pick fused into its sampling-epilogue kernel, so
    the scan body consumes the step's own token/bad vectors instead of
    running ``guarded_argmax`` over full logits.  Carry layout, freeze
    rule, donation (argnums 8, 9) and the stacked per-step bad flags
    are identical — :meth:`ServingEngine._run_window` and the host
    replay (``_apply_window``) cannot tell the two windows apart."""
    from jax import lax

    pure = exe._pure
    n_ret = exe.n_ret
    n_caches = n_ret - 3                   # logits, nxt, bad + caches
    capt = exe.capt_state
    carry_idx, const_idx = exe.state_split()

    def window(tok, pos, fin, bad, eos_ids, stop_lens, poison, bt,
               caches, cstate, const_state):
        def body(c, _):
            tok, pos, fin, bad, caches, cstate = c
            state = [None] * len(capt)
            for i, v in zip(carry_idx, cstate):
                state[i] = v
            for i, v in zip(const_idx, const_state):
                state[i] = v
            outs = pure(tok, pos, bt, poison, *caches, *state)
            nxt_raw = outs[1]
            row_bad = outs[2]
            new_caches = list(outs[3:3 + n_caches])
            new_cstate = list(outs[3 + n_caches:
                                   3 + n_caches + len(carry_idx)])
            bad2 = bad | (row_bad & jnp.logical_not(fin))
            adv = jnp.logical_not(fin | bad2)
            nxt = jnp.where(adv, nxt_raw, tok[:, 0])
            pos2 = jnp.where(adv, pos + 1, pos)
            fin2 = fin | bad2 | ((eos_ids >= 0) & (nxt == eos_ids)) \
                | (pos2 + 1 >= stop_lens)
            return (nxt[:, None], pos2, fin2, bad2, new_caches,
                    new_cstate), (nxt, bad2)

        (tok, pos, fin, bad, caches, cstate), (toks, bads) = lax.scan(
            body, (tok, pos, fin, bad, caches, cstate), None, length=K)
        return toks, bads, tok, pos, fin, bad, caches, cstate

    return jax.jit(window, donate_argnums=(8, 9))

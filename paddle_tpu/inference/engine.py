"""Continuous-batching serving engine over paged KV caches.

Capability analog of the request-level scheduling the reference's
``block_multi_head_attention`` kernel exists to serve
(``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``;
python surface ``incubate/nn/functional/block_multihead_attention.py``)
— the piece VERDICT r5 named as missing ("no request-level scheduler
that admits/retires sequences mid-decode").  Design follows the
Gemma-on-TPU serving study (arxiv 2605.25645, PAPERS.md): TPU serving
throughput comes from continuous batching over fixed-shape buckets.

Shape discipline (TPU-native):

* ONE page pool per layer ``[Hkv, total_pages, page_size, D]``; a
  free-list allocator hands pages to admitted requests and takes them
  back at retirement — HBM scales with resident tokens, not with
  ``max_slots * max_len``.  Page 0 is the reserved NULL page: inactive
  slots and packing padding write there, so retired block-table rows
  can never scribble a reassigned page.
* TWO compiled programs total, both bucket-stable:
  - the MIXED step (token budget T): prefill chunks of admitted
    requests packed together with one token from every ongoing decode —
    ``models.generation.ragged_paged_step`` serves both through one
    ragged kernel call.  Admission never stalls ongoing decodes, and a
    prompt longer than the budget prefills across consecutive steps
    (chunked prefill);
  - the DECODE window: ``decode_window`` steps scanned into one
    dispatch, slot state (tokens, positions, finished mask, page
    tables, KV pools) carried through the scan — one host round-trip
    per K tokens.
  Admission and retirement only change tensor VALUES (block tables,
  lengths, masks) between dispatches — shapes never change, so no
  per-request recompiles.
* Greedy decoding (the serving bench's measurement mode); sampling
  belongs to ``models.generate``.
"""
from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["ContinuousBatchingEngine", "CompletedRequest"]


class _Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id


class CompletedRequest:
    """Result handed back by :meth:`ContinuousBatchingEngine.step`."""

    __slots__ = ("request_id", "prompt", "tokens")

    def __init__(self, request_id, prompt, tokens):
        self.request_id = request_id
        self.prompt = prompt          # np.int32 [S]
        self.tokens = tokens          # np.int32 [<= max_new_tokens]

    @property
    def sequence(self):
        """prompt + generated tokens, the ``generate()``-comparable row."""
        return np.concatenate([self.prompt, self.tokens])


class _Slot:
    __slots__ = ("req", "phase", "pages", "cur_tok", "cur_pos",
                 "prefill_off", "out_toks", "stop_len", "eos")

    def __init__(self):
        self.req = None
        self.phase = "free"           # free | prefill | decode
        self.pages = []
        self.cur_tok = 0
        self.cur_pos = 0
        self.prefill_off = 0
        self.out_toks = []
        self.stop_len = 0
        self.eos = -1

    @property
    def len_written(self):
        """Tokens resident in the page pools (positions [0, len))."""
        if self.phase == "prefill":
            return self.prefill_off
        return self.cur_pos

    @property
    def done(self):
        if self.req is None:
            return True
        if self.phase == "prefill":
            return False
        if self.cur_pos + 1 >= self.stop_len:
            return True
        return bool(self.eos >= 0 and self.out_toks
                    and self.out_toks[-1] == self.eos)


class ContinuousBatchingEngine:
    """Request-level scheduler: ``add_request`` any time, ``step`` until
    it returns completions, or ``run`` to drain.  See the module
    docstring for the shape discipline."""

    def __init__(self, model, *, max_slots=8, page_size=16,
                 max_seq_len=None, total_pages=None, decode_window=8,
                 prefill_chunk=64, q_block=8, pages_per_block=None):
        from ..models.generation import (_decode_fn, _ragged_fn,
                                         _zero_pool)
        cfg = model.cfg
        self.model = model
        model.eval()   # the engine owns its model: serving is eval-mode
        self._decode, _, self._hard_limit = _decode_fn(model)
        self._ragged = _ragged_fn(model)
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self._hard_limit:
            self.max_seq_len = min(self.max_seq_len, cfg.max_seq_len)
        self.decode_window = int(decode_window)
        self.q_block = int(q_block)
        self.prefill_chunk = max(self.q_block, int(prefill_chunk))
        self.pages_per_block = pages_per_block
        # per-slot page-table width covers the engine's length cap
        self.np_per_seq = -(-self.max_seq_len // self.page_size)
        if total_pages is None:
            total_pages = 1 + self.max_slots * self.np_per_seq
        self.total_pages = int(total_pages)
        # token budget of the mixed step: one q_block per slot (the
        # ongoing decodes) + the prefill chunk
        self.token_budget = (self.max_slots * self.q_block
                             + self.prefill_chunk)

        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        shape = (n_kv, self.total_pages, self.page_size, cfg.head_dim)
        self._caches = [Tensor(a)
                        for a in _zero_pool(shape, 2 * cfg.num_layers)]
        self._free_pages = deque(range(1, self.total_pages))  # 0 = null
        self._bt = np.zeros((self.max_slots, self.np_per_seq), np.int32)
        self._slots = [_Slot() for _ in range(self.max_slots)]
        self._queue: deque[_Request] = deque()
        self._next_rid = 0
        self._step_fn = None
        self._mixed_fn = None
        self._decode_exe = None
        # allocator stats (page-reuse evidence for tests/bench)
        self.stats = {"admitted": 0, "retired": 0, "steps": 0,
                      "mixed_steps": 0, "decode_dispatches": 0,
                      "tokens_generated": 0, "pages_allocated": 0,
                      "peak_pages_in_use": 0}

    # ------------------------------------------------------------ API --
    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    request_id=None):
        prompt = np.asarray(
            prompt.numpy() if isinstance(prompt, Tensor) else prompt,
            np.int32).reshape(-1)
        total = prompt.size + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens > engine max_seq_len "
                f"{self.max_seq_len}")
        if request_id is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = request_id
            if isinstance(rid, int):  # auto ids must never collide
                self._next_rid = max(self._next_rid, rid + 1)
            in_flight = {r.rid for r in self._queue} | {
                s.req.rid for s in self._slots if s.req is not None}
            if rid in in_flight:
                raise ValueError(f"request_id {rid!r} already in flight")
        self._queue.append(_Request(
            rid, prompt, max_new_tokens,
            -1 if eos_token_id is None else int(eos_token_id)))
        return rid

    @property
    def has_work(self):
        return bool(self._queue) or any(
            s.req is not None for s in self._slots)

    def run(self, max_steps=10000):
        """Drain: step until every queued/resident request completes.
        Returns {request_id: CompletedRequest} in completion order."""
        done = {}
        for _ in range(max_steps):
            if not self.has_work:
                break
            for c in self.step():
                done[c.request_id] = c
        return done

    # ------------------------------------------------- scheduling -----
    def _retire(self):
        out = []
        for b, s in enumerate(self._slots):
            if s.req is None or not s.done:
                continue
            toks = s.out_toks[:s.req.max_new_tokens]
            if s.eos >= 0 and s.eos in toks:
                toks = toks[:toks.index(s.eos) + 1]
            out.append(CompletedRequest(
                s.req.rid, s.req.prompt, np.asarray(toks, np.int32)))
            self._free_pages.extend(s.pages)
            self._bt[b, :] = 0        # null page: a frozen slot's writes
            self._slots[b] = _Slot()  # can never touch a reissued page
            self.stats["retired"] += 1
        return out

    def _admit(self):
        admitted = False
        for b, s in enumerate(self._slots):
            if s.req is not None or not self._queue:
                continue
            req = self._queue[0]
            need = -(-(req.prompt.size + req.max_new_tokens)
                     // self.page_size)
            if need > len(self._free_pages):
                break                 # head-of-line: keep arrival order
            self._queue.popleft()
            pages = [self._free_pages.popleft() for _ in range(need)]
            s.req = req
            s.phase = "prefill"
            s.pages = pages
            s.prefill_off = 0
            s.out_toks = []
            s.stop_len = req.prompt.size + req.max_new_tokens
            s.eos = req.eos_token_id
            self._bt[b, :] = 0
            self._bt[b, :need] = pages
            self.stats["admitted"] += 1
            self.stats["pages_allocated"] += need
            admitted = True
        in_use = self.total_pages - 1 - len(self._free_pages)
        self.stats["peak_pages_in_use"] = max(
            self.stats["peak_pages_in_use"], in_use)
        return admitted

    def step(self):
        """One scheduling step: retire, admit, dispatch.  Returns the
        requests completed by the PREVIOUS dispatch (retirement happens
        at step boundaries)."""
        completed = self._retire()
        self._admit()
        self.stats["steps"] += 1
        if any(s.phase == "prefill" for s in self._slots):
            self._run_mixed()
        elif any(s.phase == "decode" for s in self._slots):
            self._run_decode()
        elif self._queue:
            # nothing resident and the head request STILL could not be
            # admitted: with every slot free the full page budget is
            # available, so no amount of stepping will ever serve it
            req = self._queue[0]
            need = -(-(req.prompt.size + req.max_new_tokens)
                     // self.page_size)
            raise RuntimeError(
                f"request {req.rid} needs {need} pages but the pool "
                f"only has {self.total_pages - 1}; raise total_pages "
                "or lower max_new_tokens")
        return completed

    # compiled serving programs cache ON the model (generate()'s
    # _decode_step_cache idiom): engines with the same bucket geometry
    # — page/table/pool shapes, token budget, slot count — share the
    # compiled mixed/decode programs instead of re-tracing
    def _program_cache(self):
        return self.model.__dict__.setdefault("_serving_step_cache", {})

    def _geometry(self):
        return (self.max_slots, self.page_size, self.np_per_seq,
                self.total_pages, self.token_budget, self.q_block,
                self.pages_per_block)

    # ------------------------------------------------- mixed step -----
    def _get_mixed_fn(self):
        if self._mixed_fn is None:
            key = ("mixed",) + self._geometry()
            cache = self._program_cache()
            self._mixed_fn = cache.get(key)
        if self._mixed_fn is None:
            from .. import jit as jit_mod
            from .. import ops
            model, ragged, qb = self.model, self._ragged, self.q_block
            ppb = self.pages_per_block

            def mixed(ids_t, tok_pos, tok_slot, tok_valid, kv_lens,
                      q_lens, last_idx, bt, *cs):
                import paddle_tpu as pp
                with pp.no_grad():
                    logits, new = ragged(model, ids_t, tok_pos, tok_slot,
                                         tok_valid, kv_lens, q_lens, bt,
                                         list(cs), qb, ppb)
                    lg = ops.gather(logits, last_idx)       # [B, V]
                    nxt = ops.argmax(lg, axis=-1, dtype="int32")
                return (nxt,) + tuple(new)

            self._mixed_fn = jit_mod.to_static(mixed)
            cache[key] = self._mixed_fn
        return self._mixed_fn

    def _run_mixed(self):
        """Pack one q_block-aligned segment per active slot — decode
        slots their current token, prefill slots the next prompt chunk
        that fits — and advance everything in ONE dispatch."""
        qb, T, B = self.q_block, self.token_budget, self.max_slots
        budget = T - sum(qb for s in self._slots
                         if s.phase == "decode")
        tok = np.zeros(T, np.int32)
        tpos = np.zeros(T, np.int32)
        tslot = np.zeros(T, np.int32)
        tvalid = np.zeros(T, np.int32)
        kv_lens = np.ones(B, np.int32)
        q_lens = np.zeros(B, np.int32)
        last_idx = np.zeros(B, np.int32)
        chunks = {}
        cur = 0
        for b, s in enumerate(self._slots):
            if s.phase == "decode":
                seg = [int(s.cur_tok)]
                pos0 = s.cur_pos
            elif s.phase == "prefill":
                rem = s.req.prompt.size - s.prefill_off
                take = min(rem, budget)
                while take > 0 and -(-take // qb) * qb > budget:
                    take -= 1     # q_block padding must fit the budget
                if take <= 0:
                    continue      # budget exhausted: sits out this step
                budget -= -(-take // qb) * qb
                seg = list(s.req.prompt[s.prefill_off:
                                        s.prefill_off + take])
                pos0 = s.prefill_off
                chunks[b] = take
            else:
                continue
            n = len(seg)
            tok[cur:cur + n] = seg
            tpos[cur:cur + n] = pos0 + np.arange(n)
            tslot[cur:cur + n] = b
            tvalid[cur:cur + n] = 1
            q_lens[b] = n
            kv_lens[b] = s.len_written + n
            last_idx[b] = cur + n - 1
            cur += -(-n // qb) * qb   # next segment at a q_block boundary
        fn = self._get_mixed_fn()
        args = [Tensor(jnp.asarray(tok[None, :])),
                Tensor(jnp.asarray(tpos)), Tensor(jnp.asarray(tslot)),
                Tensor(jnp.asarray(tvalid)),
                Tensor(jnp.asarray(kv_lens)),
                Tensor(jnp.asarray(q_lens)),
                Tensor(jnp.asarray(last_idx)),
                Tensor(jnp.asarray(self._bt))]
        res = fn(*args, *self._caches)
        nxt = np.asarray(res[0]._read()).reshape(-1)
        self._caches = list(res[1:])
        self.stats["mixed_steps"] += 1
        self.stats["decode_dispatches"] += 1
        for b, s in enumerate(self._slots):
            if s.req is None or q_lens[b] == 0:
                continue
            if s.phase == "decode":
                self._accept(s, int(nxt[b]))
            else:
                s.prefill_off += chunks[b]
                if s.prefill_off >= s.req.prompt.size:
                    s.phase = "decode"
                    s.cur_pos = s.req.prompt.size
                    s.cur_tok = int(nxt[b])
                    s.out_toks.append(int(nxt[b]))
                    self.stats["tokens_generated"] += 1

    def _accept(self, s, t):
        s.out_toks.append(t)
        s.cur_tok = t
        s.cur_pos += 1
        self.stats["tokens_generated"] += 1

    # ------------------------------------------------ decode window ---
    def _get_step_fn(self):
        if self._step_fn is None:
            key = ("decode",) + self._geometry()
            cache = self._program_cache()
            self._step_fn = cache.get(key)
        if self._step_fn is None:
            from .. import jit as jit_mod
            from ..models.generation import paged_slot_attention
            model, decode = self.model, self._decode
            ppb = self.pages_per_block

            def step(tok, pos, bt, *cs):
                import paddle_tpu as pp
                with pp.no_grad():
                    def attend(q, k, v, kc, vc, p):
                        return paged_slot_attention(q, k, v, kc, vc,
                                                    p, bt,
                                                    pages_per_block=ppb)
                    logits, new = decode(model, tok, pos, list(cs),
                                         attend=attend)
                return (logits,) + tuple(new)

            self._step_fn = jit_mod.to_static(step)
            self._program_cache()[key] = self._step_fn
        return self._step_fn

    def _slot_vectors(self):
        B = self.max_slots
        tok = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        fin = np.ones(B, bool)
        eos = np.full(B, -1, np.int32)
        stop = np.ones(B, np.int32)
        for b, s in enumerate(self._slots):
            if s.phase != "decode":
                continue
            tok[b, 0] = s.cur_tok
            pos[b] = s.cur_pos
            fin[b] = s.done
            eos[b] = s.eos
            stop[b] = s.stop_len
        return tok, pos, fin, eos, stop

    def _run_decode(self):
        tok, pos, fin, eos, stop = self._slot_vectors()
        step_fn = self._get_step_fn()
        if self._decode_exe is None:
            # a model-cache hit may hand us an already-compiled step
            wrapped = (step_fn if hasattr(step_fn, "_cache")
                       else getattr(step_fn, "__wrapped__", None))
            if wrapped is not None and getattr(wrapped, "_cache", None):
                self._decode_exe = next(iter(wrapped._cache.values()))
        if self._decode_exe is None:
            # first decode dispatch compiles the scalar step; its logits
            # advance every live slot by one token (host argmax)
            res = step_fn(Tensor(jnp.asarray(tok)),
                          Tensor(jnp.asarray(pos)),
                          Tensor(jnp.asarray(self._bt)), *self._caches)
            lg = np.asarray(res[0]._read())
            self._caches = list(res[1:])
            nxt = lg.argmax(-1).astype(np.int32)
            self.stats["decode_dispatches"] += 1
            for b, s in enumerate(self._slots):
                if not fin[b]:
                    self._accept(s, int(nxt[b]))
            wrapped = (step_fn if hasattr(step_fn, "_cache")
                       else getattr(step_fn, "__wrapped__", None))
            if wrapped is not None and getattr(wrapped, "_cache", None):
                self._decode_exe = next(iter(wrapped._cache.values()))
            return
        self._run_window(tok, pos, fin, eos, stop)

    def _get_window_runner(self, K):
        # cached on the executable (generate()'s idiom) so engines
        # sharing a compiled step also share its window programs
        runners = self._decode_exe.__dict__.setdefault(
            "_slot_window_cache", {})
        runner = runners.get(K)
        if runner is None:
            runner = _make_slot_window(self._decode_exe, K)
            runners[K] = runner
        return runner

    def _run_window(self, tok, pos, fin, eos, stop):
        """K scanned decode steps in one dispatch; slot state rides the
        scan carry (models/generation.py's window machinery, per-slot)."""
        exe = self._decode_exe
        K = self.decode_window
        for sync in exe.discovery.host_syncs:
            sync()
        capt = exe.capt_state
        carry_idx, const_idx = exe.state_split()
        cache_vals = [c._read() for c in self._caches]
        cstate = [capt[i]._read() for i in carry_idx]
        const_state = [capt[i]._read() for i in const_idx]
        runner = self._get_window_runner(K)
        toks, tokf, posf, finf, cache_vals, cstate = runner(
            jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(fin),
            jnp.asarray(eos), jnp.asarray(stop),
            jnp.asarray(self._bt), cache_vals, cstate, const_state)
        toks = np.asarray(toks)                       # [K, B]
        for i, v in zip(carry_idx, cstate):
            capt[i]._data = v
            capt[i]._node = None
        for t, v in zip(self._caches, cache_vals):
            t._data = v
            t._node = None
        self.stats["decode_dispatches"] += 1
        # host replay of the device stop rule (identical predicate, so
        # the accepted prefix matches the carried fin exactly)
        for b, s in enumerate(self._slots):
            if s.phase != "decode" or fin[b]:
                continue
            for k in range(K):
                t = int(toks[k, b])
                self._accept(s, t)
                if (s.eos >= 0 and t == s.eos) \
                        or s.cur_pos + 1 >= s.stop_len:
                    break


def _make_slot_window(exe, K):
    """Scan K per-slot greedy decode steps into ONE jitted dispatch.
    The carry holds (token, position, finished) PER SLOT plus caches
    and mutated captured state; finished slots freeze (position and
    token stop advancing, so their page writes keep landing on already
    owned — or null — pages)."""
    from jax import lax

    pure = exe._pure
    n_ret = exe.n_ret
    n_caches = n_ret - 1
    capt = exe.capt_state
    carry_idx, const_idx = exe.state_split()

    def window(tok, pos, fin, eos_ids, stop_lens, bt, caches, cstate,
               const_state):
        def body(c, _):
            tok, pos, fin, caches, cstate = c
            state = [None] * len(capt)
            for i, v in zip(carry_idx, cstate):
                state[i] = v
            for i, v in zip(const_idx, const_state):
                state[i] = v
            outs = pure(tok, pos, bt, *caches, *state)
            lg = outs[0].astype(jnp.float32)
            new_caches = list(outs[1:1 + n_caches])
            new_cstate = list(outs[1 + n_caches:
                                   1 + n_caches + len(carry_idx)])
            nxt = lg.argmax(-1).astype(jnp.int32)         # [B]
            adv = jnp.logical_not(fin)
            nxt = jnp.where(adv, nxt, tok[:, 0])
            pos2 = jnp.where(adv, pos + 1, pos)
            fin2 = fin | ((eos_ids >= 0) & (nxt == eos_ids)) \
                | (pos2 + 1 >= stop_lens)
            return (nxt[:, None], pos2, fin2, new_caches,
                    new_cstate), nxt

        (tok, pos, fin, caches, cstate), toks = lax.scan(
            body, (tok, pos, fin, caches, cstate), None, length=K)
        return toks, tok, pos, fin, caches, cstate

    return jax.jit(window, donate_argnums=(6, 7))

"""``paddle.inference`` parity — the deployment Predictor API (SURVEY C28).

Analog of ``python/paddle/inference/wrapper.py`` (Config, create_predictor,
Predictor/Tensor handles; native engine ``paddle/fluid/inference/api/``).
TPU-native: a Predictor wraps a ``jit.save``d StableHLO program
(TranslatedLayer) — XLA is the inference engine; Config's GPU/TensorRT
toggles are accepted and ignored (XLA owns those decisions), memory/zero-
copy handles are the program's device buffers.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Config:
    """Reference ``paddle.inference.Config(prog_file, params_file)`` or
    ``Config(model_dir)``; we key off the jit.save path prefix."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is None:
            raise ValueError("Config requires the jit.save path prefix")
        # accept either the prefix or the .pdmodel path
        self.path_prefix = str(prog_file).removesuffix(".pdmodel")
        self._switches = {}

    # accepted-for-parity toggles (XLA owns device placement/fusion)
    def enable_use_gpu(self, *a, **k):
        self._switches["gpu"] = True

    def disable_gpu(self):
        self._switches["gpu"] = False

    def enable_memory_optim(self, *a, **k):
        self._switches["memory_optim"] = True

    def switch_ir_optim(self, flag=True):
        self._switches["ir_optim"] = flag

    def enable_tensorrt_engine(self, *a, **k):
        self._switches["trt"] = True

    def set_cpu_math_library_num_threads(self, n):
        self._switches["cpu_threads"] = n


class _IOTensor:
    """Reference inference Tensor handle (copy_from_cpu/copy_to_cpu)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)

    def reshape(self, shape):
        self._value = np.asarray(self._value).reshape(shape)


class Predictor:
    """Reference ``paddle.inference.Predictor`` surface over a loaded
    StableHLO program."""

    def __init__(self, config: Config):
        from .. import jit
        self._layer = jit.load(config.path_prefix)
        n_in = len(self._layer._exported.in_avals) - len(self._layer._names)
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs = {n: _IOTensor() for n in self._input_names}
        self._outputs = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        if inputs is not None:  # list-of-arrays convenience form
            for n, v in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(v)
        args = [self._inputs[n].copy_to_cpu() for n in self._input_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for o in outs:
            h = _IOTensor()
            h.copy_from_cpu(np.asarray(o._read() if isinstance(o, Tensor)
                                       else o))
            self._outputs.append(h)
        return [h.copy_to_cpu() for h in self._outputs]

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name):
        return self._outputs[int(name.removeprefix("out"))]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


__all__ = ["Config", "Predictor", "create_predictor"]

"""``paddle.inference`` parity — the deployment Predictor API (SURVEY C28).

Analog of ``python/paddle/inference/wrapper.py`` (Config, create_predictor,
Predictor/Tensor handles; native engine ``paddle/fluid/inference/api/``).
TPU-native: a Predictor wraps a ``jit.save``d StableHLO program
(TranslatedLayer) — XLA is the inference engine.

Config toggle semantics (explicit, not silent): every accepted switch is
recorded and visible via ``Config.summary()``; the ones XLA already owns
(device placement, IR optimization, TensorRT, memory planning) are
ACCEPTED-AND-IGNORED by design and ``summary()`` says so per switch.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

# switches whose job XLA already does — accepted for API parity, ignored
_NOOP_SWITCHES = {
    "gpu": "device placement is XLA/PJRT's (runs on the jax backend)",
    "memory_optim": "XLA buffer assignment already plans memory",
    "ir_optim": "XLA optimizes the StableHLO program",
    "trt": "no TensorRT on TPU; XLA fuses instead",
    "cpu_threads": "XLA CPU thread pool is runtime-managed",
    "mkldnn": "XLA CPU backend replaces oneDNN",
}


class Config:
    """Reference ``paddle.inference.Config(prog_file, params_file)`` or
    ``Config(model_dir)``; we key off the jit.save path prefix."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is None:
            raise ValueError("Config requires the jit.save path prefix")
        # accept either the prefix or the .pdmodel path
        self.path_prefix = str(prog_file).removesuffix(".pdmodel")
        self._switches = {}

    def enable_use_gpu(self, *a, **k):
        self._switches["gpu"] = True

    def disable_gpu(self):
        self._switches["gpu"] = False

    def enable_memory_optim(self, *a, **k):
        self._switches["memory_optim"] = True

    def switch_ir_optim(self, flag=True):
        self._switches["ir_optim"] = flag

    def enable_tensorrt_engine(self, *a, **k):
        self._switches["trt"] = True

    def enable_mkldnn(self, *a, **k):
        self._switches["mkldnn"] = True

    def set_cpu_math_library_num_threads(self, n):
        self._switches["cpu_threads"] = n

    def summary(self):
        """What each set switch actually does here (reference
        ``Config.summary``)."""
        lines = [f"model: {self.path_prefix}"]
        for k, v in self._switches.items():
            why = _NOOP_SWITCHES.get(k)
            state = "accepted, NO-OP: " + why if why else f"= {v}"
            lines.append(f"{k}: {state}")
        return "\n".join(lines)


class _IOTensor:
    """Reference inference Tensor handle (copy_from_cpu/copy_to_cpu)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)

    def reshape(self, shape):
        self._value = np.asarray(self._value).reshape(shape)


class Predictor:
    """Reference ``paddle.inference.Predictor`` surface over a loaded
    StableHLO program. Input names come from the export's InputSpec names
    (``jit.save(input_spec=[InputSpec(..., name="ids")])``); unnamed
    inputs fall back to ``x{i}``."""

    def __init__(self, config: Config):
        from .. import jit
        self._layer = jit.load(config.path_prefix)
        meta = getattr(self._layer, "_meta", {})
        in_specs = meta.get("in_specs", [])
        if in_specs:
            self._input_names = [
                (nm if nm else f"x{i}")
                for i, (_shape, _dtype, nm) in enumerate(in_specs)]
        else:
            n_in = (len(self._layer._exported.in_avals)
                    - len(self._layer._names))
            self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs = {n: _IOTensor() for n in self._input_names}
        self._outputs = []
        # output names come from the export metadata (dict keys / tensor
        # names recorded by jit.save); synthesized out{i} only when the
        # export predates the out_names field
        self._output_names = list(meta.get("out_names", []))

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def _run_once(self, args):
        out = self._layer(*args)
        flat = []

        def walk(o):  # same order as jit's _flatten_tensors
            if isinstance(o, (list, tuple)):
                for v in o:
                    walk(v)
            elif isinstance(o, dict):
                for k in sorted(o):
                    walk(o[k])
            else:
                flat.append(o)

        walk(out)
        return [np.asarray(o._read() if isinstance(o, Tensor) else o)
                for o in flat]

    def run(self, inputs=None):
        if inputs is not None:  # list-of-arrays convenience form
            for n, v in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(v)
        args = [self._inputs[n].copy_to_cpu() for n in self._input_names]
        res = self._run_once(args)
        self._outputs = []
        for o in res:
            h = _IOTensor()
            h.copy_from_cpu(o)
            self._outputs.append(h)
        if len(self._output_names) != len(res):
            self._output_names = [f"out{i}" for i in range(len(res))]
        return [h.copy_to_cpu() for h in self._outputs]

    def run_batch(self, inputs, batch_size):
        """Serving helper: split axis-0 into ``batch_size`` chunks, run
        each through the compiled program, concatenate the outputs.

        Needs a symbolic batch dim in the export
        (``InputSpec([None, ...])``) when ``n % batch_size != 0`` — a
        concrete-shape export accepts only its fixed batch, so the
        residual chunk would be rejected with a shape error."""
        arrays = [np.asarray(v) for v in inputs]
        n = arrays[0].shape[0]
        parts = None
        for lo in range(0, n, batch_size):
            chunk = [a[lo:lo + batch_size] for a in arrays]
            res = self._run_once(chunk)
            if parts is None:
                parts = [[] for _ in res]
            for acc, r in zip(parts, res):
                acc.append(r)
        outs = [np.concatenate(p, axis=0) for p in (parts or [])]
        self._outputs = []
        for o in outs:
            h = _IOTensor()
            h.copy_from_cpu(o)
            self._outputs.append(h)
        if len(self._output_names) != len(outs):
            self._output_names = [f"out{i}" for i in range(len(outs))]
        return outs

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name):
        if name in self._output_names:
            return self._outputs[self._output_names.index(name)]
        # legacy synthesized names remain addressable pre-run
        return self._outputs[int(name.removeprefix("out"))]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from .engine import CompletedRequest  # noqa: E402
from .engine import ContinuousBatchingEngine  # noqa: E402
from .prefix_cache import PrefixCache  # noqa: E402
from .speculative import (DraftModelProposer, NGramProposer,  # noqa: E402
                          Proposer)
from .distserve import (DisaggServer, KVPageTransport,  # noqa: E402
                        register_decode_worker)
from .router import (FleetRouter, RpcReplica, TenantSpec,  # noqa: E402
                     register_replica_worker)

__all__ = ["Config", "Predictor", "create_predictor",
           "ContinuousBatchingEngine", "CompletedRequest",
           "PrefixCache", "Proposer", "NGramProposer",
           "DraftModelProposer", "DisaggServer", "KVPageTransport",
           "register_decode_worker", "FleetRouter", "TenantSpec",
           "RpcReplica", "register_replica_worker"]

"""paddle_tpu.io — datasets and DataLoader.

Analog of ``python/paddle/io/`` (reference ``reader.py:216`` DataLoader,
``io/dataloader/``). TPU-native pipeline notes: workers are background
*threads* feeding a bounded prefetch queue (host-side numpy work releases the
GIL; the heavy lifting is device transfer which JAX handles async), instead of
the reference's fork+shared-memory worker model that exists to dodge the GIL
around CUDA — on TPU the XLA transfer path makes that machinery unnecessary.
"""
from __future__ import annotations

import itertools
import math
import os
import queue as _queue
import threading

import numpy as np

from ..core import state
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {t.shape[0] for t in tensors}
        assert len(lens) == 1, "all tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        import bisect
        ds = bisect.bisect_right(self.cum, idx)
        prev = self.cum[ds - 1] if ds > 0 else 0
        return self.datasets[ds][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        sizes = [int(math.floor(n * l)) for l in lengths]
        rem = n - sum(sizes)
        for i in range(rem):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    assert sum(lengths) == len(dataset)
    idx = np.random.permutation(len(dataset)).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, idx[off:off + l]))
        off += l
    return out


# --- samplers -------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        yield from np.random.choice(
            len(self.weights), self.num_samples, self.replacement,
            p).tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference ``io/dataloader/batch_sampler.py`` DistributedBatchSampler:
    each rank sees a contiguous 1/nranks slice of the (optionally shuffled)
    index space."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        if num_replicas is None or rank is None:
            try:
                from .. import distributed as dist
                num_replicas = (num_replicas if num_replicas is not None
                                else dist.get_world_size())
                rank = rank if rank is not None else dist.get_rank()
            except ImportError:
                num_replicas = num_replicas or 1
                rank = rank or 0
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas
        self.local_rank = rank
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(
            math.ceil(len(dataset) * 1.0 / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank * self.num_samples:
                          (self.local_rank + 1) * self.num_samples]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# --- collate --------------------------------------------------------------
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._read()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=state.DEFAULT_DTYPE))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate batch of {type(sample)}")


class _Prefetcher:
    """Thread-based prefetch pipeline feeding a bounded queue."""

    _END = object()

    def __init__(self, it_factory, depth):
        self._q = _queue.Queue(maxsize=depth)
        self._it_factory = it_factory
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._exc = None
        self._thread.start()

    def _put(self, item):
        # bounded-blocking put that wakes up if the consumer went away
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _run(self):
        try:
            for item in self._it_factory():
                if not self._put(item):
                    return
        except BaseException as e:  # propagate to consumer
            self._exc = e
        finally:
            self._put(self._END)

    def close(self):
        self._closed = True
        # drain so a blocked producer can observe _closed
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._END:
                    if self._exc is not None:
                        raise self._exc
                    return
                yield item
        finally:
            self.close()


class DataLoader:
    """Reference ``reader.py:216``. Supports batch_sampler / batch_size+
    shuffle+drop_last, collate_fn, num_workers>0 = threaded prefetch."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._user_collate = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
            self.batch_size = None
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def _produce(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                if self.batch_size is None:
                    yield item
                    continue
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for idx_batch in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def _mp_iter(self):
        """Forked worker processes + shared-memory handoff (reference
        ``io/reader.py:216`` / ``io/dataloader/worker.py``): GIL-free
        AND crash-isolated — a worker dying in Dataset code raises in
        the trainer instead of killing it. Workers are jax-free; numpy
        nests come back and are wrapped into Tensors here."""
        from .worker import MPBatchLoader, np_collate
        collate = self._user_collate or np_collate
        if self._iterable_mode:
            src = MPBatchLoader(
                self.dataset, collate, self.num_workers,
                worker_init_fn=self.worker_init_fn, timeout=self.timeout,
                iterable=True, batch_size=self.batch_size,
                drop_last=self.drop_last).run_iterable()
        elif self.batch_sampler is None:
            src = MPBatchLoader(
                self.dataset, lambda b: b[0], self.num_workers,
                worker_init_fn=self.worker_init_fn,
                timeout=self.timeout).run(
                    [[i] for i in range(len(self.dataset))])
        else:
            src = MPBatchLoader(
                self.dataset, collate, self.num_workers,
                worker_init_fn=self.worker_init_fn,
                timeout=self.timeout).run(list(self.batch_sampler))
        to_tensor = self._user_collate is None
        for item in src:
            yield _wrap_np_nest(item) if to_tensor else item

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            if self.use_shared_memory and hasattr(os, "fork"):
                return self._mp_iter()
            depth = max(2, self.prefetch_factor * self.num_workers)
            return iter(_Prefetcher(self._produce, depth))
        return self._produce()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)


def _wrap_np_nest(obj):
    """Worker-produced numpy nest -> the Tensor nest default_collate_fn
    would have built in-process."""
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _wrap_np_nest(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_wrap_np_nest(v) for v in obj]
    return obj


def get_worker_info():
    """Reference ``paddle.io.get_worker_info``: inside a forked
    DataLoader worker, its (id, num_workers, dataset); else None."""
    from .worker import get_worker_info as _g
    return _g()

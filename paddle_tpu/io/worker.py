"""Multiprocess DataLoader workers with shared-memory batch handoff.

Capability analog of the reference's forked-worker DataLoader
(``python/paddle/io/reader.py:216``, ``io/dataloader/worker.py``):
``num_workers > 0`` with ``use_shared_memory=True`` forks worker
processes that run ``Dataset.__getitem__`` + collate OUTSIDE the GIL
and outside the trainer process (a crash in user data code cannot take
down training — the loader raises instead), handing finished batches
back through ``multiprocessing.shared_memory`` blocks (one tiny pipe
message per batch; array bytes never pass through a pipe).

TPU-specific rule (the analog of the reference's "no CUDA in forked
workers"): workers must not touch jax — a forked child inheriting the
process's TPU claim would wedge the chip. Batches are therefore
collated with a NUMPY-ONLY collate in the worker and wrapped into
framework Tensors on the trainer side. A custom ``collate_fn`` runs in
the worker and must stay numpy-pure.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue as pyqueue
import traceback
from multiprocessing import shared_memory

import numpy as np

__all__ = ["WorkerInfo", "get_worker_info", "MPBatchLoader"]

_worker_info = None


class WorkerInfo:
    """Reference ``get_worker_info()`` result: id / num_workers /
    dataset as seen inside a worker process."""

    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return f"WorkerInfo(id={self.id}, num_workers={self.num_workers})"


def get_worker_info():
    """Inside a worker: its WorkerInfo; in the trainer process: None."""
    return _worker_info


def np_collate(batch):
    """Numpy-only mirror of default_collate_fn (jax-free: safe in
    forked workers)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [np_collate(list(items)) for items in zip(*batch)]
    # datasets returning framework Tensors worked through the threaded
    # path and must keep working: pull the host value. (Creating
    # tensors inside a worker is the user touching jax there — same
    # standing as the reference's no-CUDA-in-workers rule.)
    try:
        from ..core.tensor import Tensor
        if isinstance(sample, Tensor):
            return np.stack([np.asarray(s._read()) for s in batch])
    except ImportError:
        pass
    raise TypeError(
        f"multiprocess DataLoader cannot collate {type(sample)} in a "
        "worker (return numpy/scalars/Tensors from "
        "Dataset.__getitem__, or use num_workers=0)")


def _encode(obj):
    """Batch nest -> picklable description; ndarray payloads move via
    shared memory (worker side keeps no reference)."""
    if isinstance(obj, np.ndarray) and obj.nbytes > 0:
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        dst = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        dst[...] = obj
        name = shm.name
        shm.close()
        # ownership transfers to the consumer (it unlinks after copy);
        # drop the creator-side tracker registration or every segment
        # is double-reported at worker exit
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        return ("shm", name, obj.shape, str(obj.dtype))
    if isinstance(obj, np.ndarray):
        return ("np", obj)
    if isinstance(obj, dict):
        return ("dict", {k: _encode(v) for k, v in obj.items()})
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__, [_encode(v) for v in obj])
    return ("c", obj)


def _decode(desc):
    kind = desc[0]
    if kind == "shm":
        _, name, shape, dtype = desc
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.ndarray(shape, np.dtype(dtype),
                             buffer=shm.buf).copy()
        finally:
            shm.close()
            # attaching (create=False) ALSO registered the segment with
            # the CONSUMER process's resource_tracker; that registration
            # must not outlive the unlink or the tracker reports
            # "leaked shared_memory" at interpreter shutdown
            try:
                shm.unlink()
            except FileNotFoundError:
                _untrack(shm, force=True)  # unlink never unregistered
            else:
                _untrack(shm)
        return arr
    if kind == "np":
        return desc[1]
    if kind == "dict":
        return {k: _decode(v) for k, v in desc[1].items()}
    if kind == "list":
        return [_decode(v) for v in desc[1]]
    if kind == "tuple":
        return tuple(_decode(v) for v in desc[1])
    return desc[1]


def _worker_loop(dataset, collate, task_q, result_q, wid, num_workers,
                 worker_init_fn):
    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        task = task_q.get()
        if task is None:
            return
        seq, idxs = task
        try:
            batch = collate([dataset[i] for i in idxs])
            result_q.put((seq, "ok", _encode(batch)))
        except Exception:
            result_q.put((seq, "err", traceback.format_exc()))


def _iterable_worker_loop(dataset, collate, batch_size, drop_last,
                          result_q, wid, num_workers, worker_init_fn):
    """Iterable datasets: EVERY worker streams the full dataset unless
    the dataset shards itself via ``get_worker_info()`` — the
    reference/torch contract (a dataset that ignores worker info is
    replicated num_workers times, exactly as there). The loader must
    not also stride, or a sharding dataset would lose data."""
    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    try:
        batch = []
        for item in dataset:
            if batch_size is None:
                result_q.put((None, "ok", _encode(item)))
                continue
            batch.append(item)
            if len(batch) == batch_size:
                result_q.put((None, "ok", _encode(collate(batch))))
                batch = []
        if batch and not drop_last:
            result_q.put((None, "ok", _encode(collate(batch))))
        result_q.put((None, "done", wid))
    except Exception:
        result_q.put((None, "err", traceback.format_exc()))


class MPBatchLoader:
    """Forked worker pool streaming collated batches, in order for
    map-style datasets (a reorder buffer keyed by sequence number) and
    in arrival order for iterable ones."""

    def __init__(self, dataset, collate_fn, num_workers,
                 worker_init_fn=None, timeout=0, iterable=False,
                 batch_size=None, drop_last=False):
        self._ctx = mp.get_context("fork")
        self._dataset = dataset
        self._collate = collate_fn
        self._n = int(num_workers)
        self._init_fn = worker_init_fn
        # 0/None = no deadline (reference semantics); dead workers are
        # detected by liveness polling either way
        self._timeout = timeout if timeout else None
        self._iterable = iterable
        self._batch_size = batch_size
        self._drop_last = drop_last

    # ---------------------------------------------------- map-style --
    def run(self, index_batches):
        task_q = self._ctx.SimpleQueue()
        result_q = self._ctx.SimpleQueue()
        workers = [
            self._ctx.Process(
                target=_worker_loop,
                args=(self._dataset, self._collate, task_q, result_q,
                      w, self._n, self._init_fn),
                daemon=True)
            for w in range(self._n)]
        for w in workers:
            w.start()
        pending = {}
        try:
            # bounded in-flight window: enqueue interleaved with
            # draining — enqueueing everything first deadlocks once the
            # task/result pipes fill (workers block on put, main on put)
            it = iter(index_batches)
            in_flight, want, next_seq, exhausted = 0, 0, 0, False
            while True:
                while not exhausted and in_flight < 2 * self._n + 2:
                    task = next(it, None)
                    if task is None:
                        exhausted = True
                        for _ in workers:
                            task_q.put(None)
                        break
                    task_q.put((next_seq, list(task)))
                    next_seq += 1
                    in_flight += 1
                if exhausted and in_flight == 0:
                    return
                seq, status, payload = self._get(result_q, workers)
                in_flight -= 1
                if status == "err":
                    raise RuntimeError(
                        f"DataLoader worker failed:\n{payload}")
                pending[seq] = payload
                while want in pending:
                    yield _decode(pending.pop(want))
                    want += 1
        finally:
            self._teardown(workers, result_q, pending)

    # ----------------------------------------------------- iterable --
    def run_iterable(self):
        result_q = self._ctx.SimpleQueue()
        workers = [
            self._ctx.Process(
                target=_iterable_worker_loop,
                args=(self._dataset, self._collate, self._batch_size,
                      self._drop_last, result_q, w, self._n,
                      self._init_fn),
                daemon=True)
            for w in range(self._n)]
        for w in workers:
            w.start()
        try:
            live = self._n
            while live:
                _, status, payload = self._get(result_q, workers)
                if status == "done":
                    live -= 1
                    continue
                if status == "err":
                    raise RuntimeError(
                        f"DataLoader worker failed:\n{payload}")
                yield _decode(payload)
        finally:
            self._teardown(workers, result_q, {})

    # ------------------------------------------------------ plumbing --
    def _get(self, result_q, workers):
        """SimpleQueue has no timeout: poll the underlying reader so a
        dead worker (segfault / os._exit in user code) surfaces as an
        error instead of a hang."""
        import time
        deadline = (time.monotonic() + self._timeout
                    if self._timeout else None)
        while True:
            if result_q._reader.poll(0.2):
                return result_q.get()
            dead = [w for w in workers
                    if not w.is_alive() and w.exitcode not in (0, None)]
            if dead:
                codes = [w.exitcode for w in dead]
                raise RuntimeError(
                    f"DataLoader worker(s) died with exit code(s) "
                    f"{codes} (crash in Dataset code is isolated from "
                    f"the trainer process)")
            if deadline is not None and time.monotonic() > deadline:
                raise RuntimeError("DataLoader worker timed out")

    def _teardown(self, workers, result_q, pending):
        """Kill workers AND unlink every undelivered shared-memory
        segment (the creator side unregistered from the resource
        tracker, so an early `break` would otherwise leak /dev/shm
        blocks until it fills)."""
        for payload in pending.values():
            _unlink_desc(payload)
        pending.clear()
        try:
            while result_q._reader.poll(0.1):
                item = result_q.get()
                if item[1] == "ok":
                    _unlink_desc(item[2])
        except Exception:
            pass
        for w in workers:
            if w.is_alive():
                w.terminate()
        for w in workers:
            w.join(timeout=5)


def _unlink_unregisters():
    """Whether this CPython's ``SharedMemory.unlink`` already drops the
    resource_tracker registration (3.10-era does; later versions moved
    to explicit tracking).  Probed from source once — unregistering a
    second time makes the tracker daemon print a KeyError at teardown,
    the mirror image of the leak warning."""
    global _UNLINK_UNREGISTERS
    if _UNLINK_UNREGISTERS is None:
        try:
            import inspect
            _UNLINK_UNREGISTERS = "unregister" in inspect.getsource(
                shared_memory.SharedMemory.unlink)
        except Exception:
            _UNLINK_UNREGISTERS = True  # assume modern stdlib behavior
    return _UNLINK_UNREGISTERS


_UNLINK_UNREGISTERS = None


def _untrack(shm, force=False):
    """Drop the consumer-side resource_tracker registration created by
    attaching an existing segment — ownership was the creator's and the
    segment is gone (ADVICE r5: spurious 'leaked shared_memory'
    warnings at shutdown).  ``force`` covers the path where ``unlink``
    raised (segment already gone) and so never unregistered."""
    if not force and _unlink_unregisters():
        return
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _unlink_desc(desc):
    """Release the shared memory of an undelivered encoded batch."""
    kind = desc[0]
    if kind == "shm":
        try:
            shm = shared_memory.SharedMemory(name=desc[1])
        except FileNotFoundError:
            return
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            _untrack(shm, force=True)
        else:
            _untrack(shm)
    elif kind == "dict":
        for v in desc[1].values():
            _unlink_desc(v)
    elif kind in ("list", "tuple"):
        for v in desc[1]:
            _unlink_desc(v)

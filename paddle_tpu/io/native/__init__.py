"""ctypes bindings for the native IO runtime (``loader.cc``).

Compiled on first use with g++ (cached next to the source); every entry
point degrades to a numpy fallback when the toolchain is unavailable, so
the framework stays importable anywhere. ctypes releases the GIL for the
duration of each call — the C++ thread pool overlaps preprocessing with
Python execution, the design point of the reference's C++ reader stack.
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libpaddle_tpu_io.so")
_SRC = os.path.join(_HERE, "loader.cc")
_lib = None
_lock = threading.Lock()


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        from ...utils.native_build import build_and_load
        lib = build_and_load(_SRC, _SO, flags=("-O3",))
        if lib is None:
            return None
        lib.pdtpu_normalize_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
        lib.pdtpu_nhwc_to_nchw.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
        lib.pdtpu_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64]
        lib.pdtpu_queue_new.restype = ctypes.c_void_p
        lib.pdtpu_queue_new.argtypes = [ctypes.c_int64]
        lib.pdtpu_queue_free.argtypes = [ctypes.c_void_p]
        lib.pdtpu_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_int64]
        lib.pdtpu_queue_push.restype = ctypes.c_int
        lib.pdtpu_queue_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_int64]
        lib.pdtpu_queue_pop.restype = ctypes.c_int64
        lib.pdtpu_queue_size.argtypes = [ctypes.c_void_p]
        lib.pdtpu_queue_size.restype = ctypes.c_int64
        lib.pdtpu_queue_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def normalize_batch(src: np.ndarray, mean, std, to_chw: bool = True
                    ) -> np.ndarray:
    """uint8 [N,H,W,C] -> float32 normalized [N,C,H,W] (or NHWC)."""
    assert src.dtype == np.uint8 and src.ndim == 4
    n, h, w, c = src.shape
    mean = np.ascontiguousarray(mean, np.float32).reshape(c)
    std = np.ascontiguousarray(std, np.float32).reshape(c)
    lib = load()
    if lib is None:  # numpy fallback
        out = (src.astype(np.float32) - mean) / std
        return np.ascontiguousarray(out.transpose(0, 3, 1, 2)) \
            if to_chw else out
    src = np.ascontiguousarray(src)
    shape = (n, c, h, w) if to_chw else (n, h, w, c)
    dst = np.empty(shape, np.float32)
    lib.pdtpu_normalize_u8(
        src.ctypes.data, dst.ctypes.data, n, h, w, c,
        mean.ctypes.data, std.ctypes.data, int(to_chw))
    return dst


def nhwc_to_nchw(src: np.ndarray) -> np.ndarray:
    assert src.dtype == np.float32 and src.ndim == 4
    n, h, w, c = src.shape
    lib = load()
    if lib is None:
        return np.ascontiguousarray(src.transpose(0, 3, 1, 2))
    src = np.ascontiguousarray(src)
    dst = np.empty((n, c, h, w), np.float32)
    lib.pdtpu_nhwc_to_nchw(src.ctypes.data, dst.ctypes.data, n, h, w, c)
    return dst


def gather_rows(base: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[i] = base[idx[i]] — the shuffled-batch collate hot path."""
    base = np.ascontiguousarray(base)
    idx = np.ascontiguousarray(idx, np.int64)
    lib = load()
    if lib is None:
        return base[idx].copy()
    row_bytes = base.nbytes // base.shape[0]
    out = np.empty((len(idx),) + base.shape[1:], base.dtype)
    lib.pdtpu_gather_rows(base.ctypes.data, idx.ctypes.data,
                          out.ctypes.data, len(idx), row_bytes)
    return out


class NativeQueue:
    """Bounded blocking queue of numpy payloads backed by the C++ queue
    (the reference blocking_queue.h analog). Arbitrary-array handoff:
    payloads are raw bytes; callers keep shape/dtype."""

    def __init__(self, capacity: int = 8):
        lib = load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        self._q = lib.pdtpu_queue_new(capacity)

    def push(self, arr: np.ndarray) -> bool:
        arr = np.ascontiguousarray(arr)
        return bool(self._lib.pdtpu_queue_push(self._q, arr.ctypes.data,
                                               arr.nbytes))

    def pop(self, nbytes: int, dtype=np.uint8, shape=None):
        out = np.empty(nbytes, np.uint8)
        got = self._lib.pdtpu_queue_pop(self._q, out.ctypes.data, nbytes)
        if got < 0:
            return None
        payload = out[:got]
        if shape is not None:
            payload = payload.view(dtype).reshape(shape)
        return payload

    def size(self) -> int:
        return int(self._lib.pdtpu_queue_size(self._q))

    def close(self):
        self._lib.pdtpu_queue_close(self._q)

    def __del__(self):
        try:
            self._lib.pdtpu_queue_close(self._q)
            self._lib.pdtpu_queue_free(self._q)
        except Exception:
            pass

// paddle_tpu native IO runtime.
//
// Capability analog of the reference's C++ data-loading layer (SURVEY C26
// aux: paddle/fluid/operators/reader/buffered_reader.cc, the DataLoader
// worker pool and blocking queue paddle/fluid/reader/blocking_queue.h).
// The Python DataLoader keeps its thread-prefetch design (TPU-friendly:
// one process owns the chip); this library moves the per-batch byte
// crunching (decode-normalize, layout transpose, shuffled gather) into
// multithreaded C++ that runs with the GIL released (ctypes releases it
// for the duration of the call), so preprocessing overlaps Python stepping.
//
// Build: g++ -O3 -march=native -shared -fPIC -std=c++17 -pthread
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over a transient thread pool sized to the
// hardware. Transient threads keep the library stateless (no teardown
// hazards at interpreter exit); thread-create cost is amortized over
// batch-sized work items.
template <typename F>
void parallel_for(int64_t n, F fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t workers = hw ? static_cast<int64_t>(hw) : 4;
  if (workers > n) workers = n;
  if (workers <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (int64_t w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      for (;;) {
        int64_t i = next.fetch_add(1);
        if (i >= n) return;
        fn(i);
      }
    });
  }
  for (auto &t : pool) t.join();
}

}  // namespace

extern "C" {

// uint8 HWC batch -> float32 normalized, optionally transposed to CHW.
// src: [n, h, w, c] uint8; dst: [n, c, h, w] or [n, h, w, c] float32;
// mean/std: [c].
void pdtpu_normalize_u8(const uint8_t *src, float *dst, int64_t n,
                        int64_t h, int64_t w, int64_t c, const float *mean,
                        const float *stdv, int to_chw) {
  const int64_t hw = h * w, img = hw * c;
  std::vector<float> inv(c);
  for (int64_t k = 0; k < c; ++k) inv[k] = 1.0f / stdv[k];
  parallel_for(n, [&](int64_t i) {
    const uint8_t *s = src + i * img;
    float *d = dst + i * img;
    if (to_chw) {
      for (int64_t p = 0; p < hw; ++p)
        for (int64_t k = 0; k < c; ++k)
          d[k * hw + p] = (static_cast<float>(s[p * c + k]) - mean[k]) *
                          inv[k];
    } else {
      for (int64_t p = 0; p < hw; ++p)
        for (int64_t k = 0; k < c; ++k)
          d[p * c + k] = (static_cast<float>(s[p * c + k]) - mean[k]) *
                         inv[k];
    }
  });
}

// float32 NHWC -> NCHW layout transpose.
void pdtpu_nhwc_to_nchw(const float *src, float *dst, int64_t n, int64_t h,
                        int64_t w, int64_t c) {
  const int64_t hw = h * w, img = hw * c;
  parallel_for(n, [&](int64_t i) {
    const float *s = src + i * img;
    float *d = dst + i * img;
    for (int64_t p = 0; p < hw; ++p)
      for (int64_t k = 0; k < c; ++k) d[k * hw + p] = s[p * c + k];
  });
}

// Gather rows into a contiguous batch: out[i] = base[idx[i]] for
// row_bytes-sized rows — the shuffled-batch collate hot path.
void pdtpu_gather_rows(const uint8_t *base, const int64_t *idx,
                       uint8_t *out, int64_t n, int64_t row_bytes) {
  parallel_for(n, [&](int64_t i) {
    std::memcpy(out + i * row_bytes, base + idx[i] * row_bytes, row_bytes);
  });
}

// ---- bounded blocking queue of opaque payloads (the blocking_queue.h
// analog; used by the prefetch pipeline to hand off batch buffers) ------

struct Queue {
  std::mutex m;
  std::condition_variable cv_push, cv_pop;
  std::deque<std::vector<uint8_t>> items;
  size_t cap;
  bool closed = false;
  explicit Queue(size_t c) : cap(c) {}
};

void *pdtpu_queue_new(int64_t capacity) {
  return new Queue(static_cast<size_t>(capacity));
}

void pdtpu_queue_free(void *q) { delete static_cast<Queue *>(q); }

// 1 = pushed; 0 = queue closed.
int pdtpu_queue_push(void *qp, const uint8_t *data, int64_t nbytes) {
  auto *q = static_cast<Queue *>(qp);
  std::unique_lock<std::mutex> lk(q->m);
  q->cv_push.wait(lk,
                  [&] { return q->closed || q->items.size() < q->cap; });
  if (q->closed) return 0;
  q->items.emplace_back(data, data + nbytes);
  q->cv_pop.notify_one();
  return 1;
}

// Returns payload size (copied into out, which must hold max_bytes),
// -1 = closed and drained, -2 = out buffer too small (item left queued).
int64_t pdtpu_queue_pop(void *qp, uint8_t *out, int64_t max_bytes) {
  auto *q = static_cast<Queue *>(qp);
  std::unique_lock<std::mutex> lk(q->m);
  q->cv_pop.wait(lk, [&] { return q->closed || !q->items.empty(); });
  if (q->items.empty()) return -1;
  auto &front = q->items.front();
  int64_t n = static_cast<int64_t>(front.size());
  if (n > max_bytes) return -2;
  std::memcpy(out, front.data(), n);
  q->items.pop_front();
  q->cv_push.notify_one();
  return n;
}

int64_t pdtpu_queue_size(void *qp) {
  auto *q = static_cast<Queue *>(qp);
  std::lock_guard<std::mutex> lk(q->m);
  return static_cast<int64_t>(q->items.size());
}

void pdtpu_queue_close(void *qp) {
  auto *q = static_cast<Queue *>(qp);
  std::lock_guard<std::mutex> lk(q->m);
  q->closed = true;
  q->cv_pop.notify_all();
  q->cv_push.notify_all();
}

}  // extern "C"

"""``paddle.signal`` parity — short-time Fourier transforms.

Analog of ``python/paddle/signal.py`` (stft :153, istft :309; frame/
overlap_add kernels ``paddle/phi/kernels/funcs/frame_functor.h``).
TPU-native: framing is a gather with static window counts, the FFT is the
XLA FFT HLO — the whole transform stays fusible under jit.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.dispatch import primitive


@primitive("frame")
def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames (reference ``signal.py`` frame op)."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    out = x[..., idx]                       # [..., num_frames, frame_len]
    out = jnp.swapaxes(out, -1, -2)         # [..., frame_len, num_frames]
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out


@primitive("overlap_add")
def overlap_add(x, hop_length, axis=-1):
    """Inverse of ``frame`` (reference overlap_add op)."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    frame_len, num = x.shape[-2], x.shape[-1]
    out_len = (num - 1) * hop_length + frame_len
    seg = jnp.swapaxes(x, -1, -2)           # [..., num, frame_len]
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    for i in range(num):                    # static unroll: num is static
        out = out.at[..., i * hop_length:i * hop_length + frame_len].add(
            seg[..., i, :])
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Reference ``signal.py:153``. x: [batch?, signal_len] real or complex;
    returns [batch?, n_fft//2+1 or n_fft, num_frames] complex."""
    from . import ops
    from .core.tensor import Tensor

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    @primitive("stft")
    def impl(xv, wv=None):
        v = xv
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        fr = frame.raw(v, n_fft, hop_length)        # [..., n_fft, frames]
        if wv is not None:
            w = wv
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
            fr = fr * w[:, None]
        fr = jnp.swapaxes(fr, -1, -2)               # [..., frames, n_fft]
        if onesided and not jnp.iscomplexobj(fr):
            sp = jnp.fft.rfft(fr, axis=-1)
        else:
            sp = jnp.fft.fft(fr, axis=-1)
        if normalized:
            sp = sp / jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        return jnp.swapaxes(sp, -1, -2)             # [..., freq, frames]

    args = [x] if window is None else [x, window]
    return impl(*args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Reference ``signal.py:309`` — least-squares inverse with window
    envelope normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    @primitive("istft")
    def impl(xv, wv=None):
        sp = jnp.swapaxes(xv, -1, -2)               # [..., frames, freq]
        if normalized:
            sp = sp * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            fr = jnp.fft.irfft(sp, n=n_fft, axis=-1)
        else:
            fr = jnp.fft.ifft(sp, n=n_fft, axis=-1)
            if not return_complex:
                fr = fr.real
        if wv is not None:
            w = wv
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        else:
            w = jnp.ones((n_fft,), fr.dtype)
        fr = fr * w
        fr = jnp.swapaxes(fr, -1, -2)               # [..., n_fft, frames]
        y = overlap_add.raw(fr, hop_length)
        # window-square envelope for COLA normalization
        wsq = jnp.broadcast_to((w * w)[:, None], fr.shape[-2:])
        env = overlap_add.raw(wsq, hop_length)
        y = y / jnp.where(env > 1e-11, env, 1.0)
        if center:
            pad = n_fft // 2
            y = y[..., pad:y.shape[-1] - pad]
        if length is not None:
            y = y[..., :length]
        return y

    args = [x] if window is None else [x, window]
    return impl(*args)


__all__ = ["stft", "istft", "frame", "overlap_add"]

"""Compat namespace: ``paddle.tensor`` (reference ``python/paddle/tensor/``).

On this framework every tensor op lives in ``paddle_tpu.ops`` (and is also
installed as a ``Tensor`` method); this module re-exports that surface under
the reference's module path so ``paddle.tensor.foo`` call sites work.
"""
from ..ops import *  # noqa: F401,F403
from ..ops import (  # noqa: F401
    array, creation, extra, linalg, logic, manipulation, math, random)

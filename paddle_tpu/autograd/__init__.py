"""paddle_tpu.autograd — user-facing autograd utilities.

Analog of ``python/paddle/autograd/`` (reference): ``backward``, ``grad``,
``no_grad``, and ``PyLayer`` custom-autograd (reference
``python/paddle/autograd/py_layer.py``).
"""
from __future__ import annotations

from ..core.autograd import grad, no_grad, enable_grad, set_grad_enabled  # noqa: F401
from ..core.autograd import run_backward, Node
from ..core.tensor import Tensor
from ..core import state

import jax
import jax.numpy as jnp


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def is_grad_enabled():
    return state.is_grad_enabled()


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op: subclass with static ``forward``/``backward``.

    Analog of ``paddle.autograd.PyLayer`` (reference
    ``python/paddle/autograd/py_layer.py``); wired into the tape as a Node
    whose vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)

        grad_on = state.is_grad_enabled()
        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]
        if grad_on and diff_inputs:
            def vjp_fn(cots):
                if not isinstance(cots, tuple):
                    cots = (cots,)
                gts = [Tensor(c, stop_gradient=True) if c is not None else None
                       for c in cots]
                with no_grad():
                    gin = cls.backward(ctx, *gts)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                res = []
                gi = iter(gin)
                for t in diff_inputs:
                    g = next(gi, None)
                    res.append(None if g is None else
                               (g._read() if isinstance(g, Tensor) else jnp.asarray(g)))
                return tuple(res)

            node = Node(
                cls.__name__, vjp_fn, inputs=diff_inputs,
                out_ids=[o._uid for o in outs],
                out_avals=[jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                           for o in outs],
                seq_type=None if single else tuple)
            for o in outs:
                if jnp.issubdtype(o.dtype, jnp.inexact):
                    o._node = node
                    o._stop_gradient = False
        return out


class LegacyPyLayer(PyLayer):
    pass

"""Metrics core: thread-safe Counter/Gauge/Histogram in named registries.

Design constraints (ISSUE 8 tentpole, part 1):

* ALWAYS-ON: the serving/training hot loops record through these on
  every step, so a record call is a flag check, a lock, and an int add.
  With ``PDTPU_METRICS=off`` every record call returns after ONE dict
  lookup — the off state restores pre-observability behavior (and the
  ``metrics_overhead`` bench row quantifies the on state: <= 3%
  tokens/sec on the serving workload).
* Metrics whose values back a USER-VISIBLE contract (the serving
  engine's ``stats`` snapshot) are created with ``always=True`` and
  record regardless of the flag — ``stats`` returned those numbers
  before this subsystem existed, so the flag must not zero them.
* Histograms use FIXED log-spaced buckets (``LATENCY_BUCKETS_MS`` for
  latencies, ``COUNT_BUCKETS`` for small counts): merging snapshots
  across processes/ranks is elementwise addition, never re-bucketing.
* ``Registry.snapshot()`` returns plain nested JSON (dots in metric
  names nest); ``render_prometheus()`` emits the text exposition format
  with STABLE ordering (sorted by name, then label set) and standard
  escaping, so scrapes diff cleanly across runs.

Process-global named registries come from :func:`registry` (training
telemetry lands in the ``"default"`` one); subsystems that need private
metric namespaces — one serving engine's counters must not alias
another's — instantiate :class:`Registry` directly.
"""
from __future__ import annotations

import threading
from bisect import bisect_left

from ..core import state as _state

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "registry", "snapshot",
    "render_prometheus", "enabled", "LATENCY_BUCKETS_MS",
    "COUNT_BUCKETS", "percentile_from_counts",
]

# the flags dict itself (not a copy): set_flags mutates it in place, so
# caching the reference keeps the per-record check at one dict lookup
_FLAGS = _state._FLAGS


def enabled() -> bool:
    """The ``PDTPU_METRICS`` flag (``metrics`` in ``core/state.py``)."""
    return _FLAGS["metrics"]


# fixed log-spaced latency buckets (ms): 10 us .. ~56 s, 4 per decade.
# Fixed so histograms from different runs/ranks merge elementwise.
LATENCY_BUCKETS_MS = tuple(
    round(0.01 * 10 ** (i / 4), 6) for i in range(27))

# small-count buckets (tokens per window, preemptions per request, ...)
COUNT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                 256.0, 1024.0, 4096.0)


def percentile_from_counts(buckets, counts, count, q) -> float:
    """Approximate percentile over fixed-bucket histogram state: the
    upper edge of the bucket holding the q-th observation (the fixed
    log-spaced buckets make this stable across runs).  ONE home for
    the math — :meth:`Histogram.percentile`, the SLO engine's windowed
    evaluation (``observability/slo.py``) and serving_bench's
    ``_tl_pct`` all call here, so bench columns and runtime guardrails
    can never disagree on what a p99 is.  The overflow bucket has no
    finite upper edge, so a percentile landing there is ``inf``; an
    empty histogram reads 0.0."""
    if not count or not buckets:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return float(buckets[i]) if i < len(buckets) else float("inf")
    return float("inf")


class _Metric:
    __slots__ = ("name", "help", "labels", "_always", "_lock")

    def __init__(self, name, help="", labels=None, always=False):
        self.name = str(name)
        self.help = str(help)
        # sorted tuple of (k, v) pairs: the metric's identity key
        self.labels = tuple(sorted((str(k), str(v))
                            for k, v in (labels or {}).items()))
        self._always = bool(always)
        self._lock = threading.Lock()

    def _on(self) -> bool:
        return self._always or _FLAGS["metrics"]


class Counter(_Metric):
    """Monotone int counter. ``inc`` is the API; ``set`` exists for the
    registry-backed ``stats`` adapters that need max-tracking writes."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, name, help="", labels=None, always=False):
        super().__init__(name, help, labels, always)
        self._value = 0

    def inc(self, n=1):
        if not self._on():
            return
        with self._lock:
            self._value += n

    def set(self, v):
        if not self._on():
            return
        with self._lock:
            self._value = v

    @property
    def value(self):
        return self._value

    def _snap(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value. ``set_function`` makes the gauge LAZY: the
    callable runs at snapshot/render time only, so gauges over device
    state never force a sync in the loop that owns them (the PDT112
    advice: lazily-read gauges instead of ``float(x)`` per step)."""

    __slots__ = ("_value", "_fn")
    kind = "gauge"

    def __init__(self, name, help="", labels=None, always=False):
        super().__init__(name, help, labels, always)
        self._value = 0.0
        self._fn = None

    def set(self, v):
        if not self._on():
            return
        with self._lock:
            self._value = v

    def set_function(self, fn):
        """Read ``fn()`` at snapshot time instead of a stored value."""
        self._fn = fn
        return self

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return None
        return self._value

    def _snap(self):
        return self.value


class Histogram(_Metric):
    """Fixed-bucket histogram: ``counts[i]`` holds observations with
    ``value <= buckets[i]``; ``counts[-1]`` is the overflow bucket.
    Buckets are per-instance immutable, so :meth:`merge` is elementwise."""

    __slots__ = ("buckets", "counts", "sum", "count")
    kind = "histogram"

    def __init__(self, name, help="", buckets=None, labels=None,
                 always=False):
        super().__init__(name, help, labels, always)
        bk = tuple(float(b) for b in (buckets or LATENCY_BUCKETS_MS))
        if list(bk) != sorted(bk) or len(set(bk)) != len(bk):
            raise ValueError(f"histogram buckets must be strictly "
                             f"increasing, got {bk}")
        self.buckets = bk
        self.counts = [0] * (len(bk) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        if not self._on():
            return
        v = float(v)
        i = bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def merge(self, other: "Histogram"):
        """Elementwise merge of another histogram's state (same bucket
        edges required — the point of fixing them)."""
        if other.buckets != self.buckets:
            raise ValueError(
                f"cannot merge histograms with different buckets: "
                f"{self.buckets} vs {other.buckets}")
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.sum += other.sum
            self.count += other.count
        return self

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q=0.99) -> float:
        """Approximate q-th percentile of everything observed so far
        (see :func:`percentile_from_counts` for the bucket semantics)."""
        with self._lock:
            counts = list(self.counts)
            n = self.count
        return percentile_from_counts(self.buckets, counts, n, q)

    def _snap(self):
        # under the lock: a concurrent observe must never yield a
        # snapshot whose count disagrees with its bucket counts (torn
        # reads would render invalid Prometheus histogram semantics)
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "mean": (self.sum / self.count
                             if self.count else 0.0),
                    "buckets": list(self.buckets),
                    "counts": list(self.counts)}


class Registry:
    """Named metric registry. ``counter``/``gauge``/``histogram`` are
    get-or-create keyed on ``(name, labels)`` — calling twice with the
    same identity returns the SAME object (how shared counters like the
    StepGuard skip count work), with a conflicting kind it raises."""

    def __init__(self, name=None):
        self.name = name
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, labels, always, **kw):
        key = (str(name), tuple(sorted((str(k), str(v)) for k, v in
                                       (labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, always=always,
                        **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help="", labels=None, always=False
                ) -> Counter:
        return self._get(Counter, name, help, labels, always)

    def gauge(self, name, help="", labels=None, always=False) -> Gauge:
        return self._get(Gauge, name, help, labels, always)

    def histogram(self, name, help="", buckets=None, labels=None,
                  always=False) -> Histogram:
        h = self._get(Histogram, name, help, labels, always,
                      buckets=buckets)
        if buckets is not None and \
                tuple(float(b) for b in buckets) != h.buckets:
            # silently returning the existing object would land
            # observations in the wrong buckets; mismatched buckets
            # are a hard error, same contract as Histogram.merge
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, requested {tuple(buckets)}")
        return h

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """Nested JSON: dots in metric names nest; labeled metrics nest
        one level further under ``"k=v,k2=v2"`` keys."""
        out: dict = {}
        for m in sorted(self.metrics(),
                        key=lambda m: (m.name, m.labels)):
            node = out
            parts = m.name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            leaf = m._snap()
            if m.labels:
                slot = node.setdefault(parts[-1], {})
                slot[",".join(f"{k}={v}" for k, v in m.labels)] = leaf
            else:
                node[parts[-1]] = leaf
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition: families sorted by name, series
        sorted by label set, standard HELP/label-value escaping —
        STABLE output for golden tests and clean scrape diffs."""
        by_name: dict = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            fam = sorted(by_name[name], key=lambda m: m.labels)
            pname = _prom_name(name)
            help_txt = next((m.help for m in fam if m.help), "")
            if help_txt:
                lines.append(f"# HELP {pname} {_esc_help(help_txt)}")
            lines.append(f"# TYPE {pname} {fam[0].kind}")
            for m in fam:
                lbl = _prom_labels(m.labels)
                if isinstance(m, Histogram):
                    snap = m._snap()   # one locked read: consistent
                    cum = 0
                    for edge, c in zip(snap["buckets"],
                                       snap["counts"]):
                        cum += c
                        lines.append(
                            f"{pname}_bucket"
                            f"{_prom_labels(m.labels, le=_fmt(edge))}"
                            f" {cum}")
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(m.labels, le='+Inf')}"
                        f" {snap['count']}")
                    lines.append(
                        f"{pname}_sum{lbl} {_fmt(snap['sum'])}")
                    lines.append(f"{pname}_count{lbl} {snap['count']}")
                else:
                    v = m._snap()
                    lines.append(
                        f"{pname}{lbl} {_fmt(v if v is not None else 0)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c in "_:") else "_"
                  for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _prom_labels(pairs, **extra) -> str:
    items = list(pairs) + sorted(extra.items())
    if not items:
        return ""
    return ("{" + ",".join(f'{k}="{_esc_label(str(v))}"'
                           for k, v in items) + "}")


# ------------------------------------------------------------------
# process-global named registries
# ------------------------------------------------------------------
_registries: dict[str, Registry] = {}
_reg_lock = threading.Lock()


def registry(name: str = "default") -> Registry:
    """The process-global registry under ``name`` (created on demand).
    Training/runtime telemetry records into ``registry()``; serving
    engines keep private ``Registry()`` instances (exposed through
    ``engine.metrics()``) so per-engine counters never alias."""
    with _reg_lock:
        r = _registries.get(name)
        if r is None:
            r = _registries[name] = Registry(name)
        return r


def snapshot(name: str = "default") -> dict:
    return registry(name).snapshot()


def render_prometheus(name: str = "default") -> str:
    return registry(name).render_prometheus()

"""Bench-history regression sentinel (ISSUE 14 tentpole, part 3).

The repo checks in one ``BENCH_r<N>.json`` / ``MULTICHIP_r<N>.json``
record per measurement round, but until now nothing READ them: a
round-over-round throughput dip (the r05 ``vs_baseline`` 0.983 against
r0x history) was invisible unless a human diffed JSON.  This module is
the judge:

* :func:`load_history` parses every round of both series.  Real
  records are messy — the driver stores only the trailing bytes of
  stdout, so some rounds have ``parsed: null`` and a beheaded JSON
  tail (r01/r04 in the checked-in history) — so loading is tolerant:
  ``parsed`` first, then the last parseable ``{"metric": ...}`` line
  of ``tail``, else the round is reported as skipped, never a crash.
* Records flatten to dotted numeric metrics (``value``,
  ``extra.step_time_ms``, ``extra.mfu``, ...).  Subtrees carrying a
  truthy ``cached`` marker are STALE — a re-embedded earlier
  measurement, not fresh evidence — and are excluded, as are
  config-shaped keys (batch sizes, sequence lengths) whose changes
  are workload edits, not regressions.
* Per metric, the baseline over PRIOR rounds is the MEDIAN and the
  noise scale the MAD (floored at a fraction of the baseline so a
  zero-MAD history cannot make microscopic jitter alarm).  The latest
  round regresses when it sits more than ``k`` scaled-MADs on the BAD
  side of the baseline (direction inferred from the metric name:
  ``*_ms`` / ``*time*`` / ``wall_s`` are lower-better) AND the
  relative move clears a 2% floor.  Metrics with fewer than 2 prior
  observations are reported but never judged.
* The report is SORTED, STABLE text (golden-testable, like
  ``render_prometheus``); the CLI exits nonzero iff any metric
  regressed — a CI tripwire::

      python -m paddle_tpu.observability.regress [dir] [--k 3]

* ``bench.py`` calls :func:`check_record` at the end of every round,
  so each new record self-reports ``regressions: [...]`` in its own
  JSON tail — the history judges the round that extends it.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

__all__ = ["load_history", "flatten_record", "analyze", "check_record",
           "main", "DEFAULT_K"]

DEFAULT_K = 3.0      # scaled-MAD multiplier
MAD_SCALE = 1.4826   # MAD -> sigma under normal noise
REL_FLOOR = 0.01     # MAD floor as a fraction of |baseline|
MIN_REL = 0.02       # moves under 2% of baseline never flag
MIN_PRIOR = 2        # rounds needed before a metric is judged

# config-shaped keys: changes are workload edits, not perf evidence
_SKIP_KEYS = frozenset((
    "n", "rc", "cached", "code_version", "batch", "seq_len", "iters",
    "params", "prompt_len", "new_tokens", "decode_window", "page_size",
    "max_queue", "total_pages", "requests", "spec_k", "shared_len",
    "storm_prompt", "storm_requests", "tp", "max_predictions",
    "hit_rate_cfg", "kv_cache", "pid", "round", "warmup",
))

_LOWER_BETTER_RE = re.compile(
    r"(_ms$|_ms_|ms_per|_s$|time|latency|overhead|retrace|"
    r"pages_leaked|spread|burn|loss|^PDT\d)")


def lower_is_better(name: str) -> bool:
    """Direction heuristic over the metric's leaf name: latencies,
    wall times and overhead fractions regress UP; everything else
    (throughput, MFU, ratios) regresses DOWN."""
    return bool(_LOWER_BETTER_RE.search(name.rsplit(".", 1)[-1]))


def flatten_record(rec, prefix="") -> dict:
    """Dotted numeric leaves of one round's record, skipping stale
    (``cached``) subtrees, config keys and non-numeric values."""
    out = {}
    if not isinstance(rec, dict):
        return out
    if rec.get("cached"):
        return out               # a re-embedded earlier measurement
    for k, v in rec.items():
        if k in _SKIP_KEYS:
            continue
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten_record(v, name + "."))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _parse_tail(tail: str):
    """Best-effort record from a round's stored stdout tail: last
    parseable ``{"metric": ...}`` line, else the last such JSON object
    start (the driver keeps only trailing bytes, so the enriched line
    may arrive beheaded — those rounds are skipped, not fatal)."""
    for line in reversed((tail or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            o = json.loads(line)
        except ValueError:
            continue
        if isinstance(o, dict) and "metric" in o:
            return o
    i = (tail or "").rfind('{"metric"')
    if i >= 0:
        try:
            o = json.loads(tail[i:])
            if isinstance(o, dict):
                return o
        except ValueError:
            pass
    return None


def load_round(path):
    """One round file -> (record_or_None, note)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable ({type(e).__name__})"
    rec = d.get("parsed")
    if isinstance(rec, dict) and "metric" in rec:
        return rec, ""
    rec = _parse_tail(d.get("tail", ""))
    if rec is not None:
        return rec, "recovered from tail"
    return None, "no parseable record"


def load_history(dirpath) -> dict:
    """``{series: [(round_no, path, record_or_None, note), ...]}`` for
    every ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` in ``dirpath``,
    sorted by round number."""
    out = {}
    for series in ("BENCH", "MULTICHIP"):
        rounds = []
        for path in glob.glob(os.path.join(dirpath,
                                           f"{series}_r*.json")):
            m = re.search(r"_r(\d+)\.json$", path)
            if not m:
                continue
            rec, note = load_round(path)
            rounds.append((int(m.group(1)), path, rec, note))
        rounds.sort()
        if rounds:
            out[series] = rounds
    return out


def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _fmt(v: float) -> str:
    return f"{v:.6g}"


def analyze(dirpath, k=DEFAULT_K, extra_latest=None) -> tuple:
    """Judge the newest round of each series against its priors.

    ``extra_latest`` (bench.py's hook) is a record treated as the
    newest BENCH round, with everything on disk as history.  Returns
    ``(report_text, regressed_metric_names)`` — the report is sorted
    and stable for golden tests."""
    history = load_history(dirpath)
    lines = []
    regressions = []
    series_names = sorted(set(history) | ({"BENCH"} if extra_latest
                                          else set()))
    for series in series_names:
        rounds = history.get(series, [])
        for rn, path, rec, note in rounds:
            if rec is None:
                lines.append(f"# {series} r{rn:02d} skipped: {note}")
        usable = [(rn, flatten_record(rec)) for rn, _p, rec, _n in rounds
                  if rec is not None]
        if extra_latest is not None and series == "BENCH":
            usable.append((rounds[-1][0] + 1 if rounds else 1,
                           flatten_record(extra_latest)))
        if not usable:
            lines.append(f"# {series}: no usable rounds")
            continue
        latest_rn, latest = usable[-1]
        priors = usable[:-1]
        lines.append(f"# {series}: judging r{latest_rn:02d} against "
                     f"{len(priors)} prior round(s)")
        for name in sorted(latest):
            vals = [m[name] for _rn, m in priors if name in m]
            if len(vals) < MIN_PRIOR:
                lines.append(
                    f"SKIP       {series}.{name} latest="
                    f"{_fmt(latest[name])} priors={len(vals)}")
                continue
            baseline = _median(vals)
            mad = _median([abs(v - baseline) for v in vals])
            scale = max(MAD_SCALE * mad, REL_FLOOR * abs(baseline),
                        1e-12)
            cur = latest[name]
            dev = (cur - baseline if lower_is_better(name)
                   else baseline - cur)     # positive = worse
            rel = dev / abs(baseline) if baseline else 0.0
            z = dev / scale
            bad = z > k and rel > MIN_REL
            tag = "REGRESSION" if bad else "OK        "
            lines.append(
                f"{tag} {series}.{name} latest={_fmt(cur)} "
                f"baseline={_fmt(baseline)} mad={_fmt(mad)} "
                f"z={z:+.2f}")
            if bad:
                regressions.append(f"{series}.{name}")
    return "\n".join(lines) + "\n", sorted(regressions)


def check_record(record, history_dir, k=DEFAULT_K) -> list:
    """bench.py's tail hook: judge ``record`` (the round being
    emitted) against the on-disk history; returns the regressed
    metric names (empty = clean).  BENCH-series names only — the
    record IS a BENCH round, and a standing regression in the latest
    on-disk MULTICHIP round belongs to that old round, not to the
    record self-reporting its own tail."""
    _report, regs = analyze(history_dir, k=k, extra_latest=record)
    return [r for r in regs if r.startswith("BENCH.")]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.regress",
        description="Judge the newest BENCH_*/MULTICHIP_* round "
                    "against its history (median/MAD baselines); "
                    "exits nonzero on any regression.")
    p.add_argument("dir", nargs="?", default=".",
                   help="directory holding the *_rNN.json history "
                        "(default: cwd)")
    p.add_argument("--k", type=float, default=DEFAULT_K,
                   help=f"scaled-MAD regression threshold "
                        f"(default {DEFAULT_K})")
    p.add_argument("--latest", default=None,
                   help="JSON file treated as the newest BENCH round "
                        "(judged against everything on disk)")
    args = p.parse_args(argv)
    extra = None
    if args.latest:
        with open(args.latest) as f:
            extra = json.load(f)
        if isinstance(extra, dict) and isinstance(extra.get("parsed"),
                                                  dict):
            extra = extra["parsed"]
    report, regs = analyze(args.dir, k=args.k, extra_latest=extra)
    sys.stdout.write(report)
    if regs:
        sys.stdout.write(
            f"regressions: {', '.join(regs)}\n")
        return 1
    sys.stdout.write("regressions: none\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

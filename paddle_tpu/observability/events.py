"""Structured-event ring buffer + crash flight recorder.

One process-wide bounded ring of recent structured events (ISSUE 8
tentpole, part 3).  Producers across the stack :func:`emit` into it —
the serving engine's request lifecycle, dispatch kinds, retry attempts
(``resilience/retry.py``), StepGuard skips (``resilience/guard.py``),
fault-injection firings (``resilience/faults.py``), preemption signals
(``resilience/preempt.py``) and the profiler's ``RecordEvent`` spans /
per-op dispatch events — so every subsystem's last moves land in ONE
stream.  The ring is the cheap always-on half; when a coded failure
fires (``NonFiniteLogitsError``, ``CacheIntegrityError``, the page-pool
backstop, a SIGTERM preemption) the owning code calls :func:`dump` and
the postmortem starts from the last N events instead of a bare
traceback.

Event schema (every event is one flat JSON-able dict)::

    {"seq":  int,    # process-monotone sequence number
     "ts":   float,  # time.time() wall clock (epoch seconds)
     "kind": str,    # dotted producer.kind, e.g. "serving.admitted"
     ...fields}      # producer-specific scalars (rid, slot, ms, ...)

Emission is gated on the ``PDTPU_METRICS`` flag (off = one dict lookup
and return, and :func:`dump` writes nothing), and every field must be a
plain scalar/short string — events are recorded on the hot path and
serialized only at dump time.

Dump files are JSON ``{"schema_version", "reason", "error", "time",
"pid", "rank", "host", "extra", "events": [...]}`` written to
``PDTPU_FLIGHT_DIR`` (default ``<tempdir>/paddle_tpu_flight``) as
``flight_<pid>_<seq>.json``; :func:`last_dump` returns the newest path
this process wrote.  ``schema_version`` 2 (ISSUE 12) added the
``rank``/``host`` identity fields so multi-rank flight dumps merge —
a fleet postmortem concatenates every rank's record and still knows
whose events are whose.
"""
from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time

from .metrics import enabled

__all__ = ["emit", "tail", "clear", "capacity", "set_capacity",
           "dump", "last_dump", "dump_dir", "EventRing",
           "SCHEMA_VERSION"]

# flight-record schema: v1 = PR 8 (reason/error/time/pid/extra/events);
# v2 = ISSUE 12 (adds schema_version itself + rank/host identity so
# multi-rank dumps can be merged and attributed)
SCHEMA_VERSION = 2

_DEFAULT_CAPACITY = 512


def _rank() -> int:
    """Launcher rank for dump/trace attribution (``PADDLE_TRAINER_ID``,
    0 when unset)."""
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))
    except (TypeError, ValueError):
        return 0


def _host() -> str:
    try:
        return socket.gethostname()
    except OSError:
        return "unknown"


class EventRing:
    """Bounded ring of event dicts; overwrites oldest when full."""

    def __init__(self, capacity=_DEFAULT_CAPACITY):
        self._cap = max(1, int(capacity))
        self._buf: list = [None] * self._cap
        self._seq = 0
        # REENTRANT: the preemption signal handler dumps the ring, and
        # a signal can land while the main thread is inside emit() —
        # a plain Lock would deadlock the handler against its own
        # thread. Re-entry may observe a half-applied emit; for a
        # flight record that beats hanging the eviction grace period.
        self._lock = threading.RLock()

    @property
    def capacity(self):
        return self._cap

    def emit(self, kind: str, **fields):
        if not enabled():
            return
        ev = {"seq": 0, "ts": time.time(), "kind": str(kind)}
        ev.update(fields)
        with self._lock:
            ev["seq"] = self._seq
            self._buf[self._seq % self._cap] = ev
            self._seq += 1

    def tail(self, n=None) -> list:
        """Last ``n`` events (all retained when None), oldest first."""
        with self._lock:
            seq, cap = self._seq, self._cap
            live = min(seq, cap)
            out = [self._buf[i % cap] for i in range(seq - live, seq)]
        return out if n is None else out[-int(n):]

    def clear(self):
        with self._lock:
            self._buf = [None] * self._cap
            self._seq = 0

    def resize(self, capacity):
        keep = self.tail()
        with self._lock:
            self._cap = max(1, int(capacity))
            self._buf = [None] * self._cap
            for ev in keep[-self._cap:]:
                self._buf[self._seq % self._cap] = ev
                self._seq += 1


def _env_capacity() -> int:
    """PDTPU_EVENT_RING, parsed defensively: this runs at package
    import, where a malformed value must degrade to the default, not
    make ``import paddle_tpu`` itself raise."""
    try:
        return int(os.environ.get("PDTPU_EVENT_RING",
                                  _DEFAULT_CAPACITY))
    except (TypeError, ValueError):
        return _DEFAULT_CAPACITY


_ring = EventRing(_env_capacity())


def emit(kind: str, **fields):
    """Record one structured event in the process ring (flag-gated)."""
    _ring.emit(kind, **fields)


def tail(n=None) -> list:
    return _ring.tail(n)


def clear():
    _ring.clear()


def capacity() -> int:
    return _ring.capacity


def set_capacity(n: int):
    _ring.resize(n)


# ------------------------------------------------------------------
# flight recorder
# ------------------------------------------------------------------
_last_dump: str | None = None
_dump_lock = threading.RLock()  # reentrant: see EventRing._lock
_dump_seq = 0


def dump_dir() -> str:
    """Where flight records land: ``PDTPU_FLIGHT_DIR`` (read at dump
    time so tests can redirect) or ``<tempdir>/paddle_tpu_flight``."""
    return os.environ.get(
        "PDTPU_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "paddle_tpu_flight"))


# companion suffixes a flight record may carry (the stall watchdog
# writes a Chrome trace and a faulthandler stack file next to its
# record); retention GC removes them with their record
_COMPANION_SUFFIXES = (".trace.json", ".stacks.txt")


def _gc_flight_dir(d):
    """Keep-last-K retention over ``d``'s flight records (``flight_keep``
    flag / ``PDTPU_FLIGHT_KEEP``, mirroring CheckpointManager's
    keep-last-K discipline): without it every watchdog/SLO/NaN dump
    grows the flight dir without bound.  Oldest records (by mtime) past
    the cap are deleted together with their companion files; 0 keeps
    everything (the pre-retention behavior)."""
    try:
        from ..core import state as _state
        keep = int(_state.get_flag("flight_keep"))
    except Exception:
        return
    if keep <= 0:
        return
    recs = []
    for fname in os.listdir(d):
        if not (fname.startswith("flight_") and fname.endswith(".json")) \
                or fname.endswith(_COMPANION_SUFFIXES[0]):
            continue
        p = os.path.join(d, fname)
        try:
            recs.append((os.path.getmtime(p), p))
        except OSError:
            pass
    recs.sort()
    for _, p in recs[:-keep]:
        stem = p[:-len(".json")]
        for victim in (p,) + tuple(stem + s for s in _COMPANION_SUFFIXES):
            try:
                os.remove(victim)
            except OSError:
                pass


def dump(reason: str, *, error=None, extra=None, path=None):
    """Write the ring's current contents as one JSON flight record.

    Returns the written path, or None when metrics are off (the off
    state must restore pre-observability behavior — no stray files) or
    the write itself fails (a flight recorder must never turn a
    diagnosed failure into an IO failure).  Auto-named records in the
    default dir are retention-GC'd keep-last-K (``flight_keep`` flag);
    an explicit ``path=`` is the caller's to manage.
    """
    global _last_dump, _dump_seq
    if not enabled():
        return None
    try:
        with _dump_lock:
            _dump_seq += 1
            seq = _dump_seq
        gc_dir = None
        if path is None:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{os.getpid()}_{seq:04d}.json")
            gc_dir = d
        rec = {
            "schema_version": SCHEMA_VERSION,
            "rank": _rank(),
            "host": _host(),
            "reason": str(reason),
            "error": (None if error is None
                      else f"{type(error).__name__}: {error}"),
            "error_code": getattr(type(error), "error_code", None)
            if error is not None else None,
            "time": time.time(),
            "pid": os.getpid(),
            "extra": extra or {},
            "events": _ring.tail(),
        }
        with open(path, "w") as f:
            json.dump(rec, f, indent=1, default=str)
        _last_dump = path
        emit("flight.dump", reason=str(reason), path=path)
        if gc_dir is not None:
            _gc_flight_dir(gc_dir)
        return path
    except Exception:
        return None


def last_dump():
    """Path of the newest flight record this process wrote (or None)."""
    return _last_dump

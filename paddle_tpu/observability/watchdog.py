"""Stall watchdog: a daemon-thread heartbeat monitor with flight
capture (ISSUE 14 tentpole, part 2).

A hung dispatch is the one failure the rest of the observability stack
cannot see: no event fires, no metric moves, the caller just never
returns — and on a network-attached TPU a wedged tunnel looks exactly
like a long compile.  The watchdog turns silence into evidence:

* Callers :func:`arm` an operation with a deadline (engine dispatches,
  ``DisaggServer`` handoffs, rpc invokes, ``Model.fit`` steps — the
  arm/heartbeat marks sit at the EXISTING event-emission sites, so
  ``PDTPU_METRICS=off`` keeps today's behavior bitwise: :func:`arm`
  returns a no-op token).  Long-lived operations (a fit) refresh the
  deadline with ``token.heartbeat()`` each step; bounded ones (a
  dispatch) just ``disarm()`` on completion — a clean run leaves
  nothing armed and dumps nothing.
* A daemon thread polls (``watchdog_poll_ms`` flag).  Past the
  deadline it captures EVERY thread's stack (``sys._current_frames``
  — the in-process capture; a best-effort ``faulthandler`` dump lands
  next to the record as ``*.stacks.txt`` for the raw-fd view), emits
  ``watchdog.stall`` into the event ring, dumps the flight record
  (stacks + the victim's full lifecycle timeline) and exports the
  Chrome trace alongside it (``*.trace.json``).
* When the armer asked for an interrupt (the serving engine does), the
  stalled thread gets a coded exception injected via
  ``PyThreadState_SetAsyncExc`` —
  :class:`~paddle_tpu.core.errors.EngineStallError` (PDT-E020)
  surfaces from ``engine.step()`` instead of tier-1 hanging forever.
  The injection lands at the next bytecode boundary, so it recovers
  Python-level stalls (spin loops, lock waits with timeouts, the
  ``engine_stall`` drill); a thread truly wedged inside a C call can
  only be stack-dumped, not recovered — the flight record is still
  written either way.

Deadlines come from the ``watchdog_stall_ms`` flag (0 = off; the
engine's ``watchdog_ms`` kwarg overrides per instance).  Detection
latency is deadline + one poll interval.  Size deadlines above the
worst case of the operation INCLUDING first compiles: an interrupt
that lands mid-compile aborts a compile that would have been cached,
so the next attempt recompiles and stalls again — a deadline-induced
livelock, not a hang the watchdog can fix.
"""
from __future__ import annotations

import ctypes
import sys
import threading
import time
import traceback

from ..core import state as _state
from . import events as _events
from . import metrics as _metrics

__all__ = ["arm", "arm_collective", "armed", "thread_stacks",
           "Watchdog", "NULL_TOKEN"]


def thread_stacks() -> dict:
    """Every live thread's current stack as ``{"name:ident": text}`` —
    the JSON-embeddable capture a flight record can carry (what
    ``faulthandler.dump_traceback`` prints, readable in-process)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        out[f"{names.get(ident, '?')}:{ident}"] = "".join(
            traceback.format_stack(frame))
    return out


def _async_raise(thread_id, exc_type) -> bool:
    """Inject ``exc_type`` into ``thread_id`` at its next bytecode
    boundary (CPython ``PyThreadState_SetAsyncExc``)."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_type))
    if res > 1:
        # invalid state: undo rather than poison an unknown thread
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


class _NullToken:
    """The disarmed token: every watchdog call site can hold one
    unconditionally, so metrics-off / deadline-0 costs one attribute
    call and no state."""

    __slots__ = ()
    fired = False
    dump_path = None

    def heartbeat(self):
        pass

    def disarm(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_TOKEN = _NullToken()


class _Entry:
    __slots__ = ("site", "key", "deadline_ms", "deadline", "thread_id",
                 "interrupt_exc", "extra", "fired", "dump_path",
                 "disarmed")

    def __init__(self, site, key, deadline_ms, thread_id, interrupt_exc,
                 extra):
        self.site = str(site)
        self.key = str(key)
        self.deadline_ms = float(deadline_ms)
        self.deadline = time.monotonic() + self.deadline_ms / 1e3
        self.thread_id = thread_id
        self.interrupt_exc = interrupt_exc
        self.extra = extra
        self.fired = False
        self.dump_path = None
        self.disarmed = False


class _Token:
    __slots__ = ("_wd", "_entry")

    def __init__(self, wd, entry):
        self._wd = wd
        self._entry = entry

    @property
    def fired(self):
        return self._entry.fired

    @property
    def dump_path(self):
        return self._entry.dump_path

    def heartbeat(self):
        """Refresh the deadline (one mark per completed unit of work —
        e.g. per train step); also re-arms after a fire, so a slow
        phase that recovers keeps being monitored."""
        self._wd._heartbeat(self._entry)

    def disarm(self):
        self._wd._disarm(self._entry)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disarm()
        return False


class Watchdog:
    """The monitor: armed entries + one lazy daemon poll thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: list[_Entry] = []
        self._thread = None

    # ------------------------------------------------------------ API --
    def arm(self, site, deadline_ms, *, key="", interrupt_exc=None,
            thread_id=None, extra=None):
        """Monitor one operation; returns a token (``heartbeat`` /
        ``disarm`` / context manager).  A no-op token when the deadline
        is unset or metrics are off — arming must never change
        metrics-off behavior."""
        ms = float(deadline_ms or 0.0)
        if ms <= 0 or not _metrics.enabled():
            return NULL_TOKEN
        entry = _Entry(site, key, ms,
                       threading.get_ident() if thread_id is None
                       else thread_id,
                       interrupt_exc, extra)
        with self._lock:
            self._entries.append(entry)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="pdtpu-watchdog",
                    daemon=True)
                self._thread.start()
        return _Token(self, entry)

    def armed(self) -> list:
        """``[(site, key), ...]`` of live (non-disarmed) entries — the
        clean-run assertion surface."""
        with self._lock:
            return [(e.site, e.key) for e in self._entries
                    if not e.disarmed]

    # ------------------------------------------------------ internals --
    def _heartbeat(self, entry):
        with self._lock:
            entry.deadline = time.monotonic() + entry.deadline_ms / 1e3
            entry.fired = False

    def _disarm(self, entry):
        with self._lock:
            entry.disarmed = True
            try:
                self._entries.remove(entry)
            except ValueError:
                pass
        # the fire/complete race: if the watchdog fired but its
        # injection has not been DELIVERED yet (async exceptions land
        # at bytecode boundaries), a disarm on the target thread means
        # the operation finished — clear the pending injection so it
        # cannot surface in unrelated code after this point
        if entry.fired and entry.interrupt_exc is not None \
                and entry.thread_id == threading.get_ident():
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(entry.thread_id), None)

    def _poll_s(self) -> float:
        try:
            return max(float(_state.get_flag("watchdog_poll_ms")),
                       1.0) / 1e3
        except Exception:
            return 0.02

    def _loop(self):
        while True:
            time.sleep(self._poll_s())
            now = time.monotonic()
            with self._lock:
                if not self._entries:
                    # idle: exit instead of polling forever — arm()
                    # sees _thread is None (set under this lock) and
                    # restarts the loop with the next entry
                    self._thread = None
                    return
                due = [e for e in self._entries
                       if not e.fired and not e.disarmed
                       and now > e.deadline]
                for e in due:
                    e.fired = True
            for e in due:
                try:
                    self._fire(e)
                except Exception:
                    pass     # the monitor must never take down the host

    def _fire(self, entry):
        """One stall: stacks -> ring event -> interrupt -> flight dump
        (+ Chrome trace and faulthandler companions).  The interrupt
        goes out BEFORE the dump's file IO and only after re-checking
        the entry under the lock: every millisecond between "deadline
        exceeded" and "exception injected" is a window in which the
        operation could legitimately complete, and an injection landing
        after completion discards a real result (for a donated-buffer
        dispatch, one whose buffers are already consumed).  The
        residual boundary — completion between the locked check and
        the bytecode boundary where CPython delivers the exception —
        is inherent to async injection; ``_disarm`` clears a pending
        undelivered injection on the disarming thread to keep it from
        escaping past the armed region."""
        stacks = thread_stacks()
        _events.emit("watchdog.stall", site=entry.site, key=entry.key,
                     deadline_ms=entry.deadline_ms)
        _metrics.registry().counter(
            "watchdog.stalls", "operations past their stall deadline",
            labels={"site": entry.site}).inc()
        if entry.interrupt_exc is not None:
            with self._lock:
                interrupt = not entry.disarmed
            if interrupt:
                _async_raise(entry.thread_id, entry.interrupt_exc)
        extra = {"site": entry.site, "key": entry.key,
                 "deadline_ms": entry.deadline_ms, "stacks": stacks}
        if entry.extra:
            extra.update(entry.extra)
        path = _events.dump("watchdog_stall", extra=extra)
        entry.dump_path = path
        if path and path.endswith(".json"):
            stem = path[:-len(".json")]
            try:
                from . import tracing as _tracing
                _tracing.export_trace(stem + ".trace.json")
            except Exception:
                pass
            try:
                import faulthandler
                with open(stem + ".stacks.txt", "w") as f:
                    faulthandler.dump_traceback(file=f,
                                                all_threads=True)
            except Exception:
                pass


_WD = Watchdog()


def arm(site, deadline_ms, *, key="", interrupt_exc=None,
        thread_id=None, extra=None):
    """Arm the process watchdog (module-level singleton); see
    :meth:`Watchdog.arm`."""
    return _WD.arm(site, deadline_ms, key=key,
                   interrupt_exc=interrupt_exc, thread_id=thread_id,
                   extra=extra)


def armed() -> list:
    """Live armed entries — empty after every clean run."""
    return _WD.armed()


def arm_collective(site, *, key="", deadline_ms=None, extra=None):
    """Arm one collective dispatch against a dead-peer hang (ISSUE 15).

    The deadline defaults to the ``collective_timeout_ms`` flag (0 =
    off -> NULL token, today's behavior bitwise); past it the blocked
    caller gets :class:`~paddle_tpu.core.errors.CollectiveTimeoutError`
    (PDT-E021) injected, after stacks + flight record + Chrome trace
    are captured — a dead rank surfaces as a coded, postmortem-ready
    error instead of hanging every survivor inside the psum.  Armed
    around ``Group.psum_mean``, ``DataParallel.apply_collective_grads``,
    the pipeline forward/train_batch dispatches, and the elastic
    supervisor's store-backed allreduce.  Size the deadline above the
    operation's worst case INCLUDING first compiles (see the module
    docstring's livelock note)."""
    from ..core.errors import CollectiveTimeoutError

    ms = deadline_ms
    if ms is None:
        try:
            ms = float(_state.get_flag("collective_timeout_ms"))
        except Exception:
            ms = 0.0
    return _WD.arm(site, ms, key=key,
                   interrupt_exc=CollectiveTimeoutError, extra=extra)

"""SLO engine: declarative objectives judged over the live histograms.

PR8/PR12 built the telemetry pipes — histograms, rings, traces — but
nothing *evaluated* them: a TTFT p95 blowing through its objective was
a number in a snapshot, not a signal.  This module closes the loop
(ISSUE 14 tentpole, part 1): :class:`SLOSpec` objects declare
objectives over the existing metrics (``serving.ttft_ms`` p95,
``serving.tpot_ms`` p99, queue time, goodput fraction,
``train.step_ms`` p95 — anything recorded into a
:class:`~paddle_tpu.observability.metrics.Registry`), and
:class:`SLOEngine` evaluates them over SLIDING WINDOWS with
multi-window burn-rate alerting:

* Histograms are cumulative, so a sliding window is a DELTA between
  the current bucket counts and a retained snapshot at the window's
  start — no per-observation bookkeeping rides the hot path; the
  guardrail reads the same counters the timelines already write.
* Each spec carries an ERROR BUDGET (allowed violation fraction —
  ``1 - percentile`` by construction for a pN latency objective:
  "p95 <= X" *means* "at most 5% of observations above X").  The
  burn rate is ``bad_fraction / budget``: 1.0 = spending the budget
  exactly as fast as allowed.
* Breach fires only when the burn rate exceeds the threshold on BOTH
  the fast window (confirmation — is it happening *now*?) and the
  slow window (significance — has it been happening long enough to
  matter?), the standard SRE multi-window rule that filters blips
  without missing sustained burns.  The fast window defaults to 1/12
  of the slow one (the 5m/1h convention).
* On the not-breached -> breached transition the engine emits an
  ``slo.breach`` ring event, bumps the ``slo.breaches`` counter and
  calls ``on_breach`` (the serving engine's callback dumps a flight
  record, so the postmortem starts from the minutes that burned the
  budget).  Recovery emits ``slo.recovered``.
* ``slo.budget_remaining`` / ``slo.burn_rate`` gauges (labeled by
  spec name) land in the owning registry, so
  ``engine.render_prometheus()`` exposes budget state to scrapes.

Percentile math is :func:`metrics.percentile_from_counts` — the SAME
implementation serving_bench's report columns use, so the guardrail
and the benchmark can never disagree on what a p99 is.

Everything is gated on ``PDTPU_METRICS``: with metrics off the
histograms carry no data and ``maybe_evaluate``/``status`` return
nothing — bitwise pre-guardrail behavior.
"""
from __future__ import annotations

from bisect import bisect_right
from collections import deque

from ..core import state as _state
from . import events as _events
from .metrics import (Counter, Registry, enabled,
                      percentile_from_counts)

__all__ = ["SLOSpec", "SLOEngine", "parse_slo", "SLO_SHORTHAND"]


# shorthand spec names accepted by the ``serving_slo`` flag / engine
# ``slo=`` string: name -> (kind, metric, percentile).  ``goodput`` is
# the ratio objective over the finish-reason-labeled retirement
# counters ("stop"/"length" = a request served within contract).
SLO_SHORTHAND = {
    "ttft_p95_ms": ("latency", "serving.ttft_ms", 0.95),
    "ttft_p99_ms": ("latency", "serving.ttft_ms", 0.99),
    "tpot_p95_ms": ("latency", "serving.tpot_ms", 0.95),
    "tpot_p99_ms": ("latency", "serving.tpot_ms", 0.99),
    "queue_p95_ms": ("latency", "serving.queue_ms", 0.95),
    "queue_p99_ms": ("latency", "serving.queue_ms", 0.99),
    "dispatch_p99_ms": ("latency", "serving.dispatch_ms", 0.99),
    "step_p95_ms": ("latency", "train.step_ms", 0.95),
    "step_p99_ms": ("latency", "train.step_ms", 0.99),
    "goodput": ("ratio", "serving.finished", None),
}


class SLOSpec:
    """One declarative objective.

    ``kind="latency"``: the windowed ``percentile`` of histogram
    ``metric`` must stay <= ``threshold`` (ms); the error budget is
    the allowed fraction of observations above the threshold
    (default ``1 - percentile`` — exactly what a pN objective means).

    ``kind="ratio"``: the windowed fraction of GOOD events among
    ``metric``'s labeled counters must stay >= ``objective``
    (``good_labels`` values of ``label_key`` count as good); the
    budget is ``1 - objective``.

    ``burn_threshold``: both windows' burn rate must exceed this for
    a breach (1.0 = burning the budget at exactly the allowed rate).
    """

    __slots__ = ("name", "metric", "kind", "percentile", "threshold",
                 "objective", "budget", "good_labels", "label_key",
                 "fast_window_s", "slow_window_s", "burn_threshold")

    def __init__(self, name, metric, *, kind="latency", percentile=0.95,
                 threshold=None, objective=None, budget=None,
                 good_labels=("stop", "length"), label_key="reason",
                 fast_window_s=None, slow_window_s=None,
                 burn_threshold=1.0):
        if kind not in ("latency", "ratio"):
            raise ValueError(f"SLOSpec kind must be 'latency' or "
                             f"'ratio', got {kind!r}")
        self.name = str(name)
        self.metric = str(metric)
        self.kind = kind
        self.percentile = float(percentile)
        if kind == "latency":
            if threshold is None:
                raise ValueError(f"latency SLO {name!r} needs a "
                                 "threshold (ms)")
            self.threshold = float(threshold)
            self.objective = None
            self.budget = float(budget if budget is not None
                                else 1.0 - self.percentile)
        else:
            if objective is None:
                raise ValueError(f"ratio SLO {name!r} needs an "
                                 "objective (good fraction)")
            self.objective = float(objective)
            if not 0.0 < self.objective < 1.0:
                raise ValueError(f"ratio SLO {name!r}: objective must "
                                 f"be in (0, 1), got {self.objective}")
            self.threshold = None
            self.budget = float(budget if budget is not None
                                else 1.0 - self.objective)
        if self.budget <= 0:
            raise ValueError(f"SLO {name!r}: error budget must be "
                             f"positive, got {self.budget}")
        self.good_labels = tuple(str(v) for v in good_labels)
        self.label_key = str(label_key)
        slow = float(_state.get_flag("serving_slo_window_s")
                     if slow_window_s is None else slow_window_s)
        self.slow_window_s = max(slow, 1e-9)
        self.fast_window_s = float(self.slow_window_s / 12.0
                                   if fast_window_s is None
                                   else fast_window_s)
        self.burn_threshold = float(burn_threshold)


def parse_slo(cfg) -> list:
    """Normalize an SLO configuration into ``[SLOSpec, ...]``.

    Accepts None/''/False (nothing armed), an :class:`SLOSpec`, a
    list of specs/strings, or the flag-style spec string
    ``"ttft_p95_ms=500,goodput=0.99"`` (``,`` or ``;`` separated;
    names from :data:`SLO_SHORTHAND`).  Unknown names raise — an SLO
    silently misspelled into nonexistence is the failure mode this
    subsystem exists to prevent."""
    if not cfg:
        return []
    if isinstance(cfg, SLOSpec):
        return [cfg]
    if isinstance(cfg, (list, tuple)):
        out = []
        for item in cfg:
            out.extend(parse_slo(item))
        return out
    if not isinstance(cfg, str):
        raise ValueError(f"slo spec must be a string, SLOSpec or list, "
                         f"got {type(cfg).__name__}")
    out = []
    for part in cfg.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("=")
        name = name.strip()
        if not sep or name not in SLO_SHORTHAND:
            raise ValueError(
                f"unknown SLO spec {part!r}: expected name=value with "
                f"name one of {sorted(SLO_SHORTHAND)}")
        kind, metric, pct = SLO_SHORTHAND[name]
        v = float(val)
        if kind == "latency":
            out.append(SLOSpec(name, metric, kind="latency",
                               percentile=pct, threshold=v))
        else:
            out.append(SLOSpec(name, metric, kind="ratio", objective=v))
    return out


class _Sample:
    __slots__ = ("t", "total", "bad", "counts")

    def __init__(self, t, total, bad, counts):
        self.t = t
        self.total = total
        self.bad = bad
        self.counts = counts    # tuple for latency specs, None for ratio


class _SpecState:
    """Window bookkeeping for one spec: a deque of cumulative samples
    (newest last, plus one sample at/older than the slow window so the
    window base always exists) and the breach latch."""

    def __init__(self, spec: SLOSpec, registry: Registry, clock):
        self.spec = spec
        # METRIC SOURCE vs EXPOSURE registry: train.* telemetry lives
        # in the process-global default registry (StepTimer records
        # there), so a step_* spec armed on a serving engine must read
        # from it — judging a fresh empty train.step_ms histogram in
        # the engine's private registry would make the spec silently
        # inert, the exact failure parse_slo refuses to allow.  The
        # budget/burn gauges still land on the OWNING registry.
        from . import metrics as _metrics_mod
        self._reg = (_metrics_mod.registry()
                     if spec.metric.startswith("train.")
                     else registry)
        self.hist = None
        self.good_idx = 0
        if spec.kind == "latency":
            self.hist = self._reg.histogram(spec.metric)
            # good = observations <= threshold: every bucket whose
            # upper edge sits at or under it (bucket granularity is
            # the resolution of the judgment, same as the percentile)
            self.good_idx = bisect_right(self.hist.buckets,
                                         spec.threshold)
        self.samples: deque[_Sample] = deque()
        self.breached = False
        self.g_budget = registry.gauge(
            "slo.budget_remaining",
            "error budget left in the slow window (1 = untouched)",
            labels={"slo": spec.name})
        self.g_budget.set(1.0)
        self.g_burn_fast = registry.gauge(
            "slo.burn_rate", "error-budget burn rate",
            labels={"slo": spec.name, "window": "fast"})
        self.g_burn_slow = registry.gauge(
            "slo.burn_rate", "error-budget burn rate",
            labels={"slo": spec.name, "window": "slow"})
        self.c_breach = registry.counter(
            "slo.breaches", "multi-window burn-rate breaches",
            labels={"slo": spec.name})
        # seed the window base so the first real evaluation measures
        # everything since arming, not an empty self-delta
        self.samples.append(self._sample(clock()))

    def _sample(self, now) -> _Sample:
        sp = self.spec
        if sp.kind == "latency":
            snap = self.hist._snap()     # one locked, consistent read
            counts = tuple(snap["counts"])
            total = snap["count"]
            bad = total - sum(counts[:self.good_idx])
            return _Sample(now, total, bad, counts)
        good = total = 0
        for m in self._reg.metrics():
            if m.name != sp.metric or not isinstance(m, Counter):
                continue
            v = int(m.value or 0)
            total += v
            labels = dict(m.labels)
            if labels.get(sp.label_key) in sp.good_labels:
                good += v
        return _Sample(now, total, total - good, None)

    def _base(self, cutoff) -> _Sample:
        """Newest retained sample at/older than ``cutoff`` (falling
        back to the oldest — a young series' window is its lifetime)."""
        base = self.samples[0]
        for s in self.samples:
            if s.t <= cutoff:
                base = s
            else:
                break
        return base

    def evaluate(self, now) -> dict:
        sp = self.spec
        cur = self._sample(now)
        self.samples.append(cur)
        # retention: keep exactly one sample at/older than the slow
        # window so _base always has its anchor
        while len(self.samples) >= 2 \
                and self.samples[1].t <= now - sp.slow_window_s:
            self.samples.popleft()

        def window(w):
            base = self._base(now - w)
            total = cur.total - base.total
            bad = cur.bad - base.bad
            counts = None
            if cur.counts is not None and base.counts is not None:
                counts = [a - b for a, b in zip(cur.counts, base.counts)]
            frac = bad / total if total else 0.0
            return total, bad, counts, frac

        ft, fb, fc, ffrac = window(sp.fast_window_s)
        st, sb, sc, sfrac = window(sp.slow_window_s)
        burn_fast = ffrac / sp.budget
        burn_slow = sfrac / sp.budget
        if sp.kind == "latency":
            value = percentile_from_counts(
                self.hist.buckets, sc or (), st, sp.percentile)
            ok = st == 0 or value <= sp.threshold
            target = sp.threshold
        else:
            value = 1.0 - sfrac          # good fraction, slow window
            ok = st == 0 or value >= sp.objective
            target = sp.objective
        budget_remaining = 1.0
        if st:
            budget_remaining = max(
                0.0, 1.0 - sb / (sp.budget * st))
        breached = (ft > 0 and burn_fast > sp.burn_threshold
                    and burn_slow > sp.burn_threshold)
        self.g_budget.set(round(budget_remaining, 6))
        self.g_burn_fast.set(round(burn_fast, 6))
        self.g_burn_slow.set(round(burn_slow, 6))
        status = {
            "name": sp.name, "metric": sp.metric, "kind": sp.kind,
            "ok": bool(ok), "breached": bool(breached),
            "value": float(value), "target": float(target),
            "burn_fast": float(burn_fast), "burn_slow": float(burn_slow),
            "budget_remaining": float(budget_remaining),
            "window_total": int(st),
        }
        return status


class SLOEngine:
    """Evaluate a set of :class:`SLOSpec` over one registry.

    ``maybe_evaluate(now)`` is the hot-path entry (the serving engine
    calls it once per scheduling step): one clock compare when the
    evaluation interval hasn't elapsed, a locked counter read per spec
    when it has.  ``status()`` forces an evaluation and returns the
    per-spec status dicts.  ``on_breach(status)`` fires once per
    not-breached -> breached transition."""

    def __init__(self, registry: Registry, specs, *, clock=None,
                 on_breach=None, eval_interval_s=None):
        import time as _time
        self._clock = _time.monotonic if clock is None else clock
        self._reg = registry
        self._specs = [s for s in (specs or [])]
        self._on_breach = on_breach
        if eval_interval_s is None:
            fast = min((s.fast_window_s for s in self._specs),
                       default=1.0)
            eval_interval_s = max(fast / 4.0, 0.05)
        self._interval = float(eval_interval_s)
        self._next_eval = float("-inf")
        self._states = [_SpecState(s, registry, self._clock)
                        for s in self._specs]
        self._last: list[dict] = []

    @property
    def specs(self):
        return list(self._specs)

    def maybe_evaluate(self, now=None):
        """Throttled :meth:`evaluate`; None when the interval hasn't
        elapsed or metrics are off."""
        if not self._states or not enabled():
            return None
        if now is None:
            now = self._clock()
        if now < self._next_eval:
            return None
        return self.evaluate(now)

    def evaluate(self, now=None) -> list:
        """Evaluate every spec now; returns the status list (empty
        with metrics off — there is no data to judge)."""
        if not enabled():
            return []
        if now is None:
            now = self._clock()
        self._next_eval = now + self._interval
        out = []
        for st in self._states:
            status = st.evaluate(now)
            if status["breached"] and not st.breached:
                st.breached = True
                st.c_breach.inc()
                _events.emit("slo.breach", slo=status["name"],
                             metric=status["metric"],
                             value=round(status["value"], 4),
                             target=status["target"],
                             burn_fast=round(status["burn_fast"], 4),
                             burn_slow=round(status["burn_slow"], 4))
                if self._on_breach is not None:
                    try:
                        self._on_breach(status)
                    except Exception:
                        pass   # a breach hook must never fail the loop
            elif st.breached and not status["breached"]:
                st.breached = False
                _events.emit("slo.recovered", slo=status["name"],
                             metric=status["metric"])
            out.append(status)
        self._last = out
        return out

    def status(self) -> list:
        """Current per-spec status (forces an evaluation)."""
        return self.evaluate()

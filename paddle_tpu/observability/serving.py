"""Serving-side observability: per-request timelines over engine events.

The serving engine's mixed program makes naive latency measurement
impossible from the outside: prefill chunks and decode tokens of many
requests share ONE dispatch, so per-request phase latencies must be
reconstructed from the engine's own scheduling events — which is what
:class:`ServingTimelines` does.  The engine calls the lifecycle hooks
(enqueued -> admitted -> prefill_chunk(s) -> token(s) ->
preempted/requeued -> retired) as it schedules; the timelines object

* emits the corresponding structured events into the process ring
  (``serving.enqueued`` / ``serving.admitted`` / ``serving.first_token``
  / ``serving.decode_window`` / ``serving.verify_window`` /
  ``serving.preempted`` / ``serving.retired`` — the flight recorder's
  request-level story), and
* derives the latency metrics the TPU serving literature frames
  comparisons in: queue-time, TTFT (enqueue -> first generated token),
  TPOT (steady-state inter-token), decode-tokens-per-window, plus
  preemption-count and cache-hit-token histograms labeled by
  ``finish_reason``.

Every hook early-returns when ``PDTPU_METRICS=off``; with it on, a hook
is a dict lookup, a clock read and a histogram observe — measured by
the ``metrics_overhead`` serving-bench row.

:class:`RegistryCounters` is the adapter that re-backs the engine's
``stats`` dict onto registry counters: same keys, same int values, same
iteration order, so the PR5-PR7 gauge/counter assertions hold unchanged
while ``engine.metrics()`` exposes the same numbers as a snapshot.
"""
from __future__ import annotations

import time

from . import events as _events
from .metrics import (COUNT_BUCKETS, LATENCY_BUCKETS_MS, Registry,
                      enabled)

__all__ = ["ServingTimelines", "RegistryCounters"]


class RegistryCounters:
    """Dict-like counter block backed by a :class:`Registry`.

    ``always=True`` counters: these values ARE the engine's public
    ``stats`` contract, which predates the observability runtime — the
    metrics flag must not zero them.
    """

    def __init__(self, registry: Registry, names, prefix="serving"):
        self._names = tuple(names)
        self._c = {n: registry.counter(f"{prefix}.{n}", always=True)
                   for n in self._names}

    def __getitem__(self, k):
        return self._c[k].value

    def __setitem__(self, k, v):
        self._c[k].set(v)

    def __contains__(self, k):
        return k in self._c

    def as_dict(self) -> dict:
        """Plain dict in declaration order — byte-compatible with the
        pre-observability ``dict(self._stats)``."""
        return {n: self._c[n].value for n in self._names}


class _ReqTL:
    __slots__ = ("enq", "admit", "first_tok", "last_tok", "n_toks",
                 "cache_hit_tokens")

    def __init__(self, enq):
        self.enq = enq
        self.admit = None
        self.first_tok = None
        self.last_tok = None
        self.n_toks = 0
        self.cache_hit_tokens = 0


class ServingTimelines:
    def __init__(self, registry: Registry, clock=None):
        self._clock = time.monotonic if clock is None else clock
        self._open: dict = {}
        self._reg = registry
        self._h_queue = registry.histogram(
            "serving.queue_ms", "enqueue -> first admission wait",
            LATENCY_BUCKETS_MS)
        self._h_ttft = registry.histogram(
            "serving.ttft_ms", "enqueue -> first generated token",
            LATENCY_BUCKETS_MS)
        self._h_tpot = registry.histogram(
            "serving.tpot_ms", "steady-state inter-token latency",
            LATENCY_BUCKETS_MS)
        self._h_window = registry.histogram(
            "serving.decode_tokens_per_window",
            "tokens accepted per decode-window dispatch", COUNT_BUCKETS)
        self._h_dispatch = registry.histogram(
            "serving.dispatch_ms", "per-dispatch round trip",
            LATENCY_BUCKETS_MS)
        self._h_spec = registry.histogram(
            "serving.spec_accepted_per_step",
            "tokens emitted per speculative verify step per slot "
            "(accepted drafts + the free target token)", COUNT_BUCKETS)

    # labeled (by finish_reason) metrics are created on first use — the
    # registry get-or-creates, so repeat reasons share one object
    def _finished(self, reason):
        return self._reg.counter(
            "serving.finished", "retired requests by finish_reason",
            labels={"reason": reason})

    def _h_preempt(self, reason):
        return self._reg.histogram(
            "serving.preemptions_per_request",
            "preempt-and-requeue count over a request's lifetime",
            COUNT_BUCKETS, labels={"reason": reason})

    def _h_cache_hit(self, reason):
        return self._reg.histogram(
            "serving.cache_hit_tokens_per_request",
            "prefix-cache tokens restored instead of re-prefilled",
            COUNT_BUCKETS, labels={"reason": reason})

    # --------------------------------------------------- lifecycle ----
    def enqueued(self, rid, prompt_len, max_new_tokens):
        if not enabled():
            return
        self._open[rid] = _ReqTL(self._clock())
        _events.emit("serving.enqueued", rid=rid,
                     prompt_len=int(prompt_len),
                     max_new_tokens=int(max_new_tokens))

    def admitted(self, rid, slot, cached_tokens=0, resume_len=0):
        if not enabled():
            return
        now = self._clock()
        tl = self._open.get(rid)
        if tl is not None:
            tl.cache_hit_tokens += int(cached_tokens)
            if tl.admit is None:            # first admission only: a
                tl.admit = now              # requeue is not queue time
                self._h_queue.observe((now - tl.enq) * 1e3)
        _events.emit("serving.admitted", rid=rid, slot=int(slot),
                     cached_tokens=int(cached_tokens),
                     resume_len=int(resume_len))

    def prefill_chunk(self, rid, slot, take, off):
        if not enabled():
            return
        _events.emit("serving.prefill_chunk", rid=rid, slot=int(slot),
                     tokens=int(take), offset=int(off))

    def token(self, rid):
        """One generated token accepted for ``rid`` (any dispatch
        shape). The first one closes the TTFT window."""
        if not enabled():
            return
        now = self._clock()
        tl = self._open.get(rid)
        if tl is None:
            return
        tl.n_toks += 1
        tl.last_tok = now
        if tl.first_tok is None:
            tl.first_tok = now
            self._h_ttft.observe((now - tl.enq) * 1e3)
            _events.emit("serving.first_token", rid=rid,
                         ttft_ms=round((now - tl.enq) * 1e3, 3))

    def decode_window(self, tokens, live_slots):
        if not enabled():
            return
        self._h_window.observe(int(tokens))
        _events.emit("serving.decode_window", tokens=int(tokens),
                     live_slots=int(live_slots))

    def verify_window(self, rid, proposed, accepted, emitted):
        """One slot's speculative verify outcome (ISSUE 9):
        ``proposed`` drafts submitted, ``accepted`` of them agreed
        with the target, ``emitted`` tokens advanced (accepted + the
        free target token, clipped by eos/stop)."""
        if not enabled():
            return
        self._h_spec.observe(int(emitted))
        _events.emit("serving.verify_window", rid=rid,
                     proposed=int(proposed), accepted=int(accepted),
                     emitted=int(emitted))

    def dispatch(self, kind, ms):
        if not enabled():
            return
        self._h_dispatch.observe(float(ms))
        # stamp the active trace context (ISSUE 12): the engine opens a
        # serving.dispatch span around each dispatch, so the timeline
        # event carries trace_id/parent_id — the hop-level evidence a
        # cross-worker trace (rpc-propagated) ends in
        from . import tracing as _tracing
        _events.emit("serving.dispatch", name=str(kind),
                     ms=round(float(ms), 3),
                     **_tracing.context_fields())

    def preempted(self, rid, tokens_done):
        if not enabled():
            return
        _events.emit("serving.preempted", rid=rid,
                     tokens_done=int(tokens_done))

    def migrated(self, rid, direction, pages=0, phase=""):
        """A live migration moved ``rid`` across engines (ISSUE 20).
        ``direction`` is ``"out"`` — this engine silently relinquished
        the request (no finish reason: its open timeline closes here
        and the DESTINATION's timeline carries the request to
        retirement) — or ``"in"`` (restored here)."""
        if direction == "out":
            self._open.pop(rid, None)
        if not enabled():
            return
        _events.emit("serving.migrated", rid=rid,
                     direction=str(direction), pages=int(pages),
                     phase=str(phase))

    def retired(self, rid, reason, n_tokens, preemptions=0):
        if not enabled():
            self._open.pop(rid, None)
            return
        tl = self._open.pop(rid, None)
        self._finished(reason).inc()
        self._h_preempt(reason).observe(int(preemptions))
        if tl is not None:
            self._h_cache_hit(reason).observe(tl.cache_hit_tokens)
            if tl.admit is None:
                # retired WITHOUT ever being admitted (deadline expired
                # in the queue, queued cancel): its whole life was
                # queue time. Overload understates queueing without
                # this — the longest waits are exactly the expired ones
                self._h_queue.observe((self._clock() - tl.enq) * 1e3)
            if tl.first_tok is not None and tl.n_toks >= 2:
                self._h_tpot.observe(
                    (tl.last_tok - tl.first_tok) * 1e3
                    / (tl.n_toks - 1))
        _events.emit("serving.retired", rid=rid, finish_reason=reason,
                     tokens=int(n_tokens), preemptions=int(preemptions))

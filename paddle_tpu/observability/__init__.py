"""``paddle_tpu.observability`` — unified metrics + structured events.

A low-overhead, always-on observability runtime (ISSUE 8): one metrics
registry, one structured-event stream, one crash flight recorder —
instead of per-subsystem ``stats`` dicts and ad-hoc host timers.  The
``PDTPU_METRICS`` flag (``metrics`` in ``core/state.py``, on by
default) gates every record call; off makes each one a near-no-op and
restores pre-observability behavior bitwise (metrics backing the
serving engine's public ``stats`` contract are ``always=True`` and
record regardless).

Pieces
------
* ``metrics``   — thread-safe :class:`Counter`/:class:`Gauge`/
  :class:`Histogram` (fixed log-spaced buckets so snapshots merge
  elementwise) in process-global named registries
  (:func:`registry`); ``snapshot()`` nested JSON and a stable
  Prometheus text exporter (:func:`render_prometheus`).
* ``events``    — a bounded ring of recent structured events
  (:func:`emit`/:func:`tail`) fed by the serving engine, the resilience
  runtime (retries, StepGuard skips, fault firings, preemption
  signals) and the profiler; :func:`dump` writes the ring as a JSON
  flight record when a coded failure fires.
* ``serving``   — :class:`ServingTimelines` reconstructs per-request
  phase latencies (queue-time, TTFT, TPOT, decode-tokens-per-window,
  preemption / cache-hit histograms labeled by finish reason) from
  engine scheduling events — the ragged mixed program batches many
  requests into one dispatch, so host-side ``time.time()`` wrapping
  cannot attribute phases; the engine's own events can.
* ``steptimer`` — :class:`StepTimer` training telemetry (step wall
  histogram, retrace counter over ``Executable.trace_count``,
  tokens/sec + MFU estimate gauges, fused-optimizer bucket dispatch
  counter) hooked into ``hapi.Model.fit`` and ``Optimizer.step``.
* ``tracing``   — distributed tracing (ISSUE 12): :func:`span` /
  :func:`traced` write ``span.begin``/``span.end`` pairs into the ring
  with a propagatable trace context (``trace_id``/``span_id``/
  ``parent_id``) carried through ``distributed/rpc`` calls
  (:class:`tracing.RemoteTraceContext`) and stamped onto the engine's
  dispatch events; :func:`export_trace` renders the ring — spans,
  serving lifecycle, fault/guard/retry events — as Chrome/Perfetto
  trace-event JSON, one track per rank/thread/engine slot.
* ``slo``       — SLO guardrails (ISSUE 14): declarative
  :class:`~paddle_tpu.observability.slo.SLOSpec` objectives (TTFT/TPOT/
  queue percentiles, goodput fraction, ``train.step_ms``) evaluated by
  :class:`~paddle_tpu.observability.slo.SLOEngine` over SLIDING WINDOWS
  of the existing histograms (cumulative-count deltas — nothing new on
  the hot path) with multi-window error-budget burn-rate alerting;
  breaches emit ``slo.breach`` and trigger a flight dump, and
  ``slo.budget_remaining`` / ``slo.burn_rate`` gauges ride
  ``render_prometheus``.  Armed on serving engines via the
  ``serving_slo`` flag / ``slo=`` kwarg (``engine.slo_status()``).
* ``watchdog``  — stall watchdog (ISSUE 14): daemon-thread heartbeat
  monitor armed around engine dispatches, DisaggServer handoffs, rpc
  invokes and ``Model.fit`` steps (``watchdog_stall_ms`` flag); past
  the deadline it captures every thread's stack, dumps the flight
  record + Chrome trace, emits ``watchdog.stall``, and (for the
  engine) injects a coded ``EngineStallError`` (PDT-E020) into the
  stalled dispatch instead of letting ``step()`` hang forever.
* ``regress``   — bench-history regression sentinel (ISSUE 14):
  ``python -m paddle_tpu.observability.regress`` judges the newest
  ``BENCH_*``/``MULTICHIP_*`` round against noise-aware median/MAD
  baselines over the prior rounds (tolerating the truncated records
  real history contains, excluding ``cached`` stale subtrees), prints
  a stable sorted report and exits nonzero on regression; ``bench.py``
  calls :func:`regress.check_record` so every new round self-reports
  ``regressions: [...]`` in its JSON tail.
* ``aggregate`` — fleet-wide metrics (ISSUE 12):
  :func:`fleet_snapshot` publishes/gathers every rank's registry
  snapshot through the rendezvous ``TCPStore`` (straggler-tolerant
  timeout), merges elementwise (Counter sums, ``Histogram.merge``
  semantics, Gauges per-rank-labeled) and derives cross-rank skew —
  ``train.step_ms`` p50 spread, slowest-rank + slowest-phase
  attribution, ``overlap_frac`` per rank.

Event schema
------------
Every event is one flat JSON-able dict::

    {"seq": int, "ts": float, "kind": str, ...fields}

``seq`` is process-monotone, ``ts`` is ``time.time()``.  Kinds in use
(producers in parentheses; fields beyond rid/slot are scalars):

    serving.enqueued      rid, prompt_len, max_new_tokens   (engine)
    serving.admitted      rid, slot, cached_tokens, resume_len
    serving.prefill_chunk rid, slot, tokens, offset
    serving.first_token   rid, ttft_ms
    serving.decode_window tokens, live_slots
    serving.dispatch      name (mixed|decode|window|cow), ms
    serving.preempted     rid, tokens_done
    serving.retired       rid, finish_reason, tokens, preemptions
    serving.cache_evict   page, evictions              (prefix cache LRU)
    serving.nan_poison    rid, slot    (engine_nan_decode drill firing)
    retry.attempt         attempt, error, kind?         (resilience.retry)
    guard.step_skip       streak                        (StepGuard)
    fault.fired           site, key                     (faults.check)
    preempt.signal        signum                        (preempt handler)
    span                  name, dur_us                  (RecordEvent)
    op                    name, dur_us                  (dispatch hook,
                                                         while profiling)
    flight.dump           reason, path                  (flight recorder)
    span.begin            name, span_id, trace_id, tname,
                          parent_id?, ...attrs          (tracing.span)
    span.end              name, span_id, trace_id, dur_us, error?
    compile.begin/end     (as span.begin/end, name="compile": fn,
                          n_inputs, n_state, n_donated) (jit build)
    compile.retrace       fn, count, cause          (jit._Executable)
    rpc.client/rpc.server (as spans: fn, to/rank)   (distributed/rpc)
    slo.breach            slo, metric, value, target, burn_fast,
                          burn_slow                 (slo.SLOEngine)
    slo.recovered         slo, metric               (slo.SLOEngine)
    watchdog.stall        site, key, deadline_ms    (watchdog)

Flight records are JSON files under ``PDTPU_FLIGHT_DIR`` (default
``<tempdir>/paddle_tpu_flight``); see ``events.dump``.  Flight-record
SCHEMA v2 (ISSUE 12): dumps carry ``schema_version`` plus ``rank`` /
``host`` identity fields so multi-rank dumps merge attributably; v1
records are identified by the ABSENCE of ``schema_version``.
``last_dump()`` semantics are unchanged.
"""
from __future__ import annotations

from . import events  # noqa: F401
from . import metrics  # noqa: F401
from .events import dump, dump_dir, emit, last_dump, tail  # noqa: F401
from .metrics import (COUNT_BUCKETS, LATENCY_BUCKETS_MS,  # noqa: F401
                      Counter, Gauge, Histogram, Registry, enabled,
                      registry, render_prometheus, snapshot)
from .serving import RegistryCounters, ServingTimelines  # noqa: F401
from .steptimer import StepTimer, device_peak_flops  # noqa: F401
from . import tracing  # noqa: F401
from .tracing import (export_trace, render_trace, span,  # noqa: F401
                      traced)
from . import aggregate  # noqa: F401
from .aggregate import fleet_snapshot  # noqa: F401
from . import slo  # noqa: F401
from .slo import SLOEngine, SLOSpec, parse_slo  # noqa: F401
from . import watchdog  # noqa: F401
from . import regress  # noqa: F401

# events.dump is the flight recorder; keep a namespaced alias so call
# sites read as what they do: flight.dump(...)
from . import events as flight  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "registry",
    "snapshot", "render_prometheus", "enabled", "LATENCY_BUCKETS_MS",
    "COUNT_BUCKETS", "emit", "tail", "dump", "last_dump", "dump_dir",
    "flight", "events", "metrics", "ServingTimelines",
    "RegistryCounters", "StepTimer", "device_peak_flops",
    "tracing", "span", "traced", "export_trace", "render_trace",
    "aggregate", "fleet_snapshot",
    "slo", "SLOEngine", "SLOSpec", "parse_slo", "watchdog", "regress",
]

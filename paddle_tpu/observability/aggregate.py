"""Fleet-wide metrics aggregation over the TCP store (ISSUE 12).

PR 8's registries are per-process islands: every rank of a DP×TP×PP
job (or every disaggregated prefill/decode worker) holds its own
``train.step_ms`` histogram and ``overlap_frac`` gauge, and nothing
answers "which rank is the straggler, and in which phase" without
ssh-ing around.  :func:`fleet_snapshot` closes that: every rank
publishes its registry snapshot through the rendezvous ``TCPStore``
(the transport ``distributed/rpc`` already bootstraps from; every
store op rides the store's own bounded ``resilience.retry``), gathers
the fleet's snapshots with a straggler-tolerant timeout (a dead rank
becomes a ``missing`` entry, not a hang), and merges them:

* **Counters** sum.
* **Histograms** merge elementwise — the fixed log-spaced buckets
  exist precisely so cross-rank merge is addition
  (``Histogram.merge`` semantics, applied to serialized snapshots).
* **Gauges** keep per-rank identity: a ``rank=N`` label is appended,
  because averaging ``overlap_frac`` across ranks would hide exactly
  the straggler the gauge exists to expose.

On top of the merge, :func:`derive_skew` computes the cross-rank
attribution the TPU-vs-GPU serving comparisons and disaggregated
prefill/decode designs (PAPERS.md #2/#4) frame their tuning in:
per-rank ``train.step_ms`` p50/mean, the p50 spread, the slowest
rank, its slowest *phase* (which ``train.*`` component histogram —
opt/comm/compile — exceeds the fleet median by the largest ratio)
and ``overlap_frac`` per rank.

Gating: with ``PDTPU_METRICS=off`` :func:`fleet_snapshot` returns
``{}`` without touching the store — the flag's cheap-no-op contract.

Single-controller note: one SPMD host is one rank; ``fleet_snapshot()``
with no store degenerates to the local snapshot (used by the
``hybrid_bench`` ``gpt_3d`` row), and multi-host jobs pass the
launcher's store + ``world_size``/``rank``.
"""
from __future__ import annotations

import json
import math

from . import metrics as _metrics
from .events import SCHEMA_VERSION
from .metrics import enabled
from .tracing import trace_host, trace_rank

__all__ = [
    "fleet_snapshot", "publish_snapshot", "gather_snapshots",
    "merge_snapshots", "derive_skew", "SNAP_PREFIX",
]

SNAP_PREFIX = "__obs/snap"

# the per-phase train component histograms derive_skew attributes a
# slow rank to (step_ms is the whole; these are its parts)
_PHASE_HISTS = ("train.opt_step_ms", "train.comm_ms",
                "train.compile_ms")


def _local_payload(registry=None, rank=None) -> dict:
    """This process's registry serialized for cross-rank merge: a FLAT
    metric list keeping each metric's ``kind`` — the nested
    ``snapshot()`` JSON drops the counter/gauge distinction the merge
    rules need."""
    reg = registry if registry is not None else _metrics.registry()
    mts = []
    for m in sorted(reg.metrics(), key=lambda m: (m.name, m.labels)):
        e = {"name": m.name, "kind": m.kind,
             "labels": [list(kv) for kv in m.labels]}
        if m.kind == "histogram":
            s = m._snap()
            e.update(count=s["count"], sum=s["sum"],
                     buckets=s["buckets"], counts=s["counts"])
        else:
            v = m._snap()
            e["value"] = v if isinstance(v, (int, float, bool)) \
                or v is None else str(v)
        mts.append(e)
    return {"schema_version": SCHEMA_VERSION,
            "rank": trace_rank() if rank is None else int(rank),
            "host": trace_host(), "metrics": mts}


def _key(prefix, generation, rank) -> str:
    return f"{prefix}/{generation}/{rank}" if generation is not None \
        else f"{prefix}/{rank}"


def publish_snapshot(store, rank, registry=None, *, generation=None,
                     prefix=SNAP_PREFIX):
    """Publish this rank's snapshot under the store key; ``set`` rides
    the store's bounded retry (``TCPStore._call``)."""
    payload = json.dumps(_local_payload(registry, rank=rank),
                         sort_keys=True)
    store.set(_key(prefix, generation, rank), payload.encode())


def gather_snapshots(store, world_size, *, timeout=5.0,
                     generation=None, prefix=SNAP_PREFIX):
    """Read every rank's published snapshot.  ``timeout`` is the
    per-rank straggler budget: a rank that never published lands in
    the returned ``missing`` list instead of stalling the fleet view
    (its counters are simply absent from the merge — counters and
    histograms only grow, so the merged view is a valid lower bound)."""
    snaps: dict[int, dict] = {}
    missing: list[int] = []
    for r in range(int(world_size)):
        try:
            raw = store.get(_key(prefix, generation, r),
                            timeout=timeout)
            snaps[r] = json.loads(raw.decode())
        except (TimeoutError, ValueError, KeyError):
            missing.append(r)
    return snaps, missing


# ------------------------------------------------------------- merge --
def _label_str(labels) -> str:
    return ",".join(f"{k}={v}" for k, v in labels)


def _nest(out, name, labels, leaf):
    node = out
    parts = name.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    if labels:
        node.setdefault(parts[-1], {})[_label_str(labels)] = leaf
    else:
        node[parts[-1]] = leaf


def merge_snapshots(snaps: dict) -> dict:
    """Elementwise merge of ``{rank: payload}`` into one nested
    snapshot (``Registry.snapshot()`` shape): counters sum, histograms
    add bucket-for-bucket (mismatched buckets raise, the
    ``Histogram.merge`` contract), gauges fan out under an appended
    ``rank=N`` label."""
    counters: dict = {}
    hists: dict = {}
    gauges: dict = {}
    for r in sorted(snaps):
        for m in snaps[r].get("metrics", []):
            labels = tuple(tuple(kv) for kv in m.get("labels", []))
            key = (m["name"], labels)
            if m["kind"] == "counter":
                counters[key] = counters.get(key, 0) + m.get("value", 0)
            elif m["kind"] == "histogram":
                h = hists.get(key)
                if h is None:
                    hists[key] = {"count": m["count"], "sum": m["sum"],
                                  "buckets": list(m["buckets"]),
                                  "counts": list(m["counts"])}
                else:
                    if list(m["buckets"]) != h["buckets"]:
                        raise ValueError(
                            f"cannot merge histogram {m['name']!r}: "
                            f"rank {r} buckets {m['buckets']} != "
                            f"{h['buckets']}")
                    h["count"] += m["count"]
                    h["sum"] += m["sum"]
                    for i, c in enumerate(m["counts"]):
                        h["counts"][i] += c
            else:   # gauge: per-rank labels
                gauges[(m["name"],
                        labels + (("rank", str(r)),))] = m.get("value")
    out: dict = {}
    for (name, labels), v in sorted(counters.items()):
        _nest(out, name, labels, v)
    for (name, labels), h in sorted(hists.items()):
        h["mean"] = h["sum"] / h["count"] if h["count"] else 0.0
        _nest(out, name, labels, h)
    for (name, labels), v in sorted(gauges.items()):
        _nest(out, name, labels, v)
    return out


# -------------------------------------------------------------- skew --
def _find_metric(payload, name, kind):
    for m in payload.get("metrics", []):
        if m["name"] == name and m["kind"] == kind \
                and not m.get("labels"):
            return m
    return None


def _hist_quantile(m, q):
    """Bucket-resolution quantile: the upper edge of the first bucket
    whose cumulative count reaches ``q`` (inf for the overflow bucket)
    — deterministic, merge-consistent, good enough for spread/argmax."""
    if m is None or not m.get("count"):
        return None
    target = q * m["count"]
    cum = 0
    for edge, c in zip(m["buckets"], m["counts"]):
        cum += c
        if cum >= target:
            return float(edge)
    return math.inf


def derive_skew(snaps: dict, metric="train.step_ms") -> dict:
    """Cross-rank skew over ``{rank: payload}``: per-rank p50/mean of
    ``metric``, the p50 spread, slowest-rank attribution (rank AND the
    ``train.*`` phase histogram most above the fleet median), plus
    ``train.overlap_frac`` per rank."""
    p50: dict = {}
    mean: dict = {}
    phase_means: dict = {}
    overlap: dict = {}
    for r in sorted(snaps):
        m = _find_metric(snaps[r], metric, "histogram")
        qv = _hist_quantile(m, 0.5)
        if qv is not None:
            p50[r] = qv
            mean[r] = round(m["sum"] / m["count"], 4)
        for ph in _PHASE_HISTS:
            hm = _find_metric(snaps[r], ph, "histogram")
            if hm is not None and hm.get("count"):
                phase_means.setdefault(ph, {})[r] = \
                    hm["sum"] / hm["count"]
        g = _find_metric(snaps[r], "train.overlap_frac", "gauge")
        if g is not None:
            overlap[r] = g.get("value")
    out = {"metric": metric,
           "p50_ms": p50, "mean_ms": mean,
           "overlap_frac": overlap,
           "slowest_rank": None, "slowest_phase": None,
           "p50_spread_ms": 0.0}
    if p50:
        finite = {r: v for r, v in p50.items() if math.isfinite(v)}
        ranked = finite or p50
        # slowest by p50, ties broken by mean then lowest rank
        slowest = max(sorted(ranked),
                      key=lambda r: (ranked[r], mean.get(r, 0.0)))
        out["slowest_rank"] = slowest
        vals = list(finite.values())
        if vals:
            spread = max(vals) - min(vals)
            out["p50_spread_ms"] = round(spread, 4)
            if min(vals) > 0:
                out["p50_spread_frac"] = round(spread / min(vals), 4)
        # phase attribution: which component histogram of the slowest
        # rank sits furthest above the OTHER ranks' median of that
        # phase — the slowest rank's own value must be excluded or a
        # 2-rank fleet's median IS its max and every ratio caps at 1.0
        # (attribution would degenerate to _PHASE_HISTS order)
        worst_ratio = 0.0
        for ph, per_rank in phase_means.items():
            if slowest not in per_rank or len(per_rank) < 2:
                continue
            others = sorted(v for r2, v in per_rank.items()
                            if r2 != slowest)
            med = others[len(others) // 2]
            if med > 0:
                ratio = per_rank[slowest] / med
                if ratio > worst_ratio:
                    worst_ratio = ratio
                    out["slowest_phase"] = ph
        if out["slowest_phase"] is None and phase_means:
            # single-rank fleets / no comparable phase data: largest
            # absolute component of the slowest rank
            best = max((ph for ph in phase_means
                        if slowest in phase_means[ph]),
                       key=lambda ph: phase_means[ph][slowest],
                       default=None)
            out["slowest_phase"] = best
    return out


def fleet_snapshot(store=None, world_size=None, rank=None,
                   registry=None, *, timeout=5.0, generation=None,
                   prefix=SNAP_PREFIX) -> dict:
    """One call answers "which rank is the straggler, in which phase":
    publish this rank's registry snapshot, gather every rank's through
    the TCP store (straggler-tolerant ``timeout`` per rank), and
    return ``{merged, skew, ranks, missing, ...}``.

    Collective when ``store``+``world_size`` are given (every rank
    calls it; all ranks get the fleet view — store reads are cheap);
    with no store it degenerates to the local single-rank view.
    ``generation`` namespaces repeat collections; without it ranks
    overwrite their key in place (snapshots are monotone, so a mixed
    read is a valid lower bound).  Returns ``{}`` when metrics are
    off (cheap no-op)."""
    if not enabled():
        return {}
    rank = trace_rank() if rank is None else int(rank)
    if store is None or not world_size or int(world_size) <= 1:
        snaps = {rank: _local_payload(registry, rank=rank)}
        missing: list[int] = []
        world_size = 1
    else:
        publish_snapshot(store, rank, registry,
                         generation=generation, prefix=prefix)
        snaps, missing = gather_snapshots(
            store, world_size, timeout=timeout,
            generation=generation, prefix=prefix)
    return {
        "schema_version": SCHEMA_VERSION,
        "world_size": int(world_size),
        "rank": rank,
        "ranks": sorted(snaps),
        "missing": missing,
        "hosts": {r: snaps[r].get("host") for r in sorted(snaps)},
        "merged": merge_snapshots(snaps),
        "skew": derive_skew(snaps),
    }

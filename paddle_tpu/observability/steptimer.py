"""Training step telemetry: wall-time, retraces, tokens/sec, MFU.

``hapi.Model.fit`` owns a :class:`StepTimer` per fit and calls
:meth:`StepTimer.step` once per completed train step (per-batch path,
windowed path and epoch tails alike); custom loops can do the same.
The timer records into the process-global ``"default"`` registry:

* ``train.step_ms``        — step wall-time histogram (log buckets)
* ``train.steps``          — completed steps counter
* ``train.retraces``       — RE-traces of the compiled train step past
  the first compile (``Executable.trace_count`` deltas): a steady-state
  increment here is the shape/weakref churn regression the jit cache
  guards warn about, surfaced as a counter a dashboard can alert on
* ``train.tokens_per_sec`` — online throughput gauge (EMA-free: last
  completed step's tokens / wall)
* ``train.mfu``            — model-flops-utilization estimate gauge,
  ``6 * n_params * tokens/sec / peak_flops`` (the standard LM
  approximation); 0.0 when the device's peak is unknown (CPU)

``Optimizer.step`` feeds the same registry from its own side:
``train.opt_step_ms`` (eager update wall time) and
``train.fused_bucket_dispatches`` (flat-bucket kernel launches per
fused step — the PR4 O(buckets) claim as a live counter).

The overlap grad-sync scheduler (``distributed/overlap.py``, ISSUE 11)
adds ``train.comm_ms`` (per-bucket collective wall histogram),
``train.overlap_frac`` (fraction of collective time hidden under
backward, last step), ``train.bucket_syncs`` and
``train.overlap_bytes``.

With ``PDTPU_METRICS=off`` every call is a flag check and return.  The
optional one-line log (``metrics_log_every`` flag / ``log_every``
kwarg) goes through the ``paddle_tpu.observability`` logger every N
steps.
"""
from __future__ import annotations

import logging
import time

from . import metrics as _metrics
from .metrics import LATENCY_BUCKETS_MS, enabled

__all__ = ["StepTimer", "device_peak_flops", "note_optimizer_step"]

_log = logging.getLogger("paddle_tpu.observability")

# bf16 peak TFLOP/s by TPU device kind (vendor specs) — the MFU
# denominator; None (CPU / unknown) leaves the mfu gauge at 0.0
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def device_peak_flops():
    """Peak FLOP/s of device 0 (None when unknown, e.g. CPU)."""
    try:
        import jax
        kind = str(getattr(jax.devices()[0], "device_kind", ""))
    except Exception:
        return None
    for k, v in _PEAK_TFLOPS.items():
        if k.lower() in kind.lower():
            return v * 1e12
    return None


class StepTimer:
    def __init__(self, registry=None, prefix="train", n_params=None,
                 peak_flops=None, log_every=None):
        from ..core import state as _state
        reg = registry or _metrics.registry()
        self.n_params = int(n_params) if n_params else None
        self.peak_flops = (device_peak_flops() if peak_flops is None
                           else peak_flops)
        self.log_every = int(_state.get_flag("metrics_log_every")
                             if log_every is None else log_every)
        self._h_step = reg.histogram(
            prefix + ".step_ms", "train step wall time",
            LATENCY_BUCKETS_MS)
        self._c_steps = reg.counter(prefix + ".steps",
                                    "completed train steps")
        self._c_retrace = reg.counter(
            prefix + ".retraces",
            "compiled-train-step re-traces past the first compile")
        self._g_tps = reg.gauge(prefix + ".tokens_per_sec",
                                "tokens consumed per second (online)")
        self._g_mfu = reg.gauge(
            prefix + ".mfu", "model-flops-utilization estimate "
            "(6*N*tokens/sec over device peak)")
        self._t = None
        self._base_traces = None
        self._seen = 0

    def mark(self):
        """(Re)arm the step clock without recording — call after a
        pause (eval pass, checkpoint) so the gap isn't a 'step'."""
        self._t = time.perf_counter() if enabled() else None

    def step(self, tokens=None, trace_count=None):
        """One completed train step. ``tokens``: tokens this step
        consumed (throughput/MFU gauges); ``trace_count``: current
        total ``Executable.trace_count`` of the compiled step."""
        if not enabled():
            self._t = None
            return
        now = time.perf_counter()
        if self._t is not None:
            dt = now - self._t
            self._h_step.observe(dt * 1e3)
            self._c_steps.inc()
            self._seen += 1
            if tokens and dt > 0:
                tps = float(tokens) / dt
                self._g_tps.set(round(tps, 1))
                if self.peak_flops and self.n_params:
                    self._g_mfu.set(round(
                        6.0 * self.n_params * tps / self.peak_flops, 4))
        if trace_count is not None:
            if self._base_traces is None:
                # the first observation is the compile itself, not a
                # regression — count deltas from here
                self._base_traces = int(trace_count)
            elif trace_count > self._base_traces:
                self._c_retrace.inc(int(trace_count) - self._base_traces)
                self._base_traces = int(trace_count)
        if self.log_every and self._seen \
                and self._seen % self.log_every == 0:
            _log.info(
                "step %d: %.2f ms/step (mean), %.1f tok/s, mfu %.3f, "
                "retraces %d", self._seen, self._h_step.mean,
                float(self._g_tps.value or 0.0),
                float(self._g_mfu.value or 0.0), self._c_retrace.value)
        self._t = now


# cached metric handles for the optimizer-side hook (one-time lookups)
_opt_hist = None
_bucket_counter = None


def note_optimizer_step(wall_ms, fused_buckets=0):
    """Record one eager optimizer update: wall time histogram plus the
    fused flat-bucket dispatch count (0 = per-param path)."""
    global _opt_hist, _bucket_counter
    if not enabled():
        return
    if _opt_hist is None:
        reg = _metrics.registry()
        _opt_hist = reg.histogram(
            "train.opt_step_ms", "eager optimizer.step wall time",
            LATENCY_BUCKETS_MS)
        _bucket_counter = reg.counter(
            "train.fused_bucket_dispatches",
            "fused flat-bucket update kernels launched")
    _opt_hist.observe(float(wall_ms))
    if fused_buckets:
        _bucket_counter.inc(int(fused_buckets))

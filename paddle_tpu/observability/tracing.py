"""Distributed tracing: spans over the event ring + Perfetto export.

The distributed half of the observability runtime (ISSUE 12, tentpole
part 1).  PR 8 gave every process an event ring; this module gives the
ring *structure*: a :func:`span` context manager (and :func:`traced`
decorator) writes ``span.begin``/``span.end`` pairs carrying a
propagatable trace context — ``trace_id`` names one logical operation
end-to-end, ``span_id``/``parent_id`` nest the work inside it — and
:func:`export_trace` renders the whole ring (spans, serving lifecycle
events, fault/guard/retry events, profiler ops) as Chrome/Perfetto
trace-event JSON, one track per rank / thread / engine slot.

Context propagation
-------------------
The context is thread-local.  :func:`inject` captures it as a plain
dict; :func:`attach` re-establishes it in another thread/process so
spans opened there become children of the remote caller's span.
``distributed/rpc`` propagates automatically: ``rpc_sync``/``rpc_async``
wrap the outgoing callable in :class:`RemoteTraceContext` (picklable,
rides the existing ``(fn, args, kwargs)`` wire frame unchanged), and
the serving engine stamps the active context onto its
``serving.dispatch`` events — so a trace started at an admission
front-end survives the hop to a prefill worker and into the dispatch
that served it.

Gating
------
Everything here is gated on the ``PDTPU_METRICS`` flag: with it off,
``span()`` returns after one dict lookup and emits nothing, ``inject``
returns ``None``, rpc payloads go out UNWRAPPED (bitwise
pre-observability wire behavior) and ``export_trace`` writes nothing —
the cheap-no-op contract the flag promises everywhere else.

Event kinds (see the package docstring for the full schema)::

    span.begin   name, span_id, parent_id?, trace_id, tname, ...attrs
    span.end     name, span_id, trace_id, dur_us, error?
    compile.retrace  fn, count, cause        (jit._Executable)

Export format
-------------
:func:`render_trace` returns the Chrome trace-event dict
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``); timestamps are
microseconds relative to the earliest event, span begin/end pairs fuse
into complete ("X") events, everything else becomes thread-scoped
instants.  Output is STABLE (sorted events, sorted keys) so a golden
test can pin it byte-for-byte, same contract as
``render_prometheus()``.  Load the file at ``ui.perfetto.dev`` or
``chrome://tracing``.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import events as _events
from .metrics import LATENCY_BUCKETS_MS, enabled
from .metrics import registry as _registry

__all__ = [
    "span", "traced", "inject", "attach", "context_fields",
    "current_trace_id", "RemoteTraceContext", "render_trace",
    "export_trace", "trace_rank", "trace_host",
]


def trace_rank() -> int:
    """This process's rank for trace/flight attribution: the launcher's
    ``PADDLE_TRAINER_ID`` (0 when unset — single-process labs).  One
    home with the flight recorder's identity fields (``events._rank``)
    so traces and dumps always attribute consistently."""
    return _events._rank()


def trace_host() -> str:
    return _events._host()


# ---------------------------------------------------------------------
# trace context: thread-local (trace_id, open-span stack)
# ---------------------------------------------------------------------
class _Ctx(threading.local):
    def __init__(self):
        self.trace_id = None
        self.stack: list = []   # open span ids, innermost last


_ctx = _Ctx()
_id_lock = threading.Lock()
_next_id = 0


def _new_id() -> int:
    global _next_id
    with _id_lock:
        _next_id += 1
        return _next_id


def _reset():
    """Test hook: fresh ids + context (deterministic goldens)."""
    global _next_id
    with _id_lock:
        _next_id = 0
    _ctx.trace_id = None
    _ctx.stack = []


def current_trace_id():
    return _ctx.trace_id


def inject():
    """The active context as a plain dict to carry across a boundary
    (rpc payload, store value), or None when no span is open (or
    metrics are off)."""
    if not enabled() or not _ctx.stack:
        return None
    return {"trace_id": _ctx.trace_id, "span_id": _ctx.stack[-1]}


def context_fields() -> dict:
    """Trace fields to stamp onto an adjacent structured event (the
    engine's ``serving.dispatch``): ``{}`` outside any span."""
    if not _ctx.stack:
        return {}
    return {"trace_id": _ctx.trace_id, "parent_id": _ctx.stack[-1]}


class attach:
    """Re-establish a remote caller's context for a scope: spans opened
    inside become children of ``ctx["span_id"]`` under the caller's
    ``trace_id``.  A None/invalid ctx attaches nothing (no-op)."""

    def __init__(self, ctx):
        self._ctx = ctx if (isinstance(ctx, dict)
                            and "trace_id" in ctx
                            and "span_id" in ctx) else None
        self._saved = None

    def __enter__(self):
        if self._ctx is not None and enabled():
            self._saved = (_ctx.trace_id, _ctx.stack)
            _ctx.trace_id = self._ctx["trace_id"]
            _ctx.stack = [self._ctx["span_id"]]
        return self

    def __exit__(self, *exc):
        if self._saved is not None:
            _ctx.trace_id, _ctx.stack = self._saved
            self._saved = None
        return False


class span:
    """``with span("compile", fn="step"): ...`` — one begin/end pair in
    the event ring, exception-safe (the end event records the error
    type and still pops the stack), near-no-op when metrics are off.

    The FIRST span on a thread starts a new trace (fresh ``trace_id``);
    nested spans inherit it and point ``parent_id`` at the enclosing
    span.  Attrs must be plain scalars/short strings (ring contract)
    and must not shadow the event schema fields (``kind``/``seq``/
    ``ts``/``name``/``span_id``/``trace_id``/``parent_id``/``tname``).
    """

    __slots__ = ("name", "attrs", "span_id", "_t0", "_on", "_root")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self._on = False

    def __enter__(self):
        if not enabled():
            return self
        self._on = True
        self._root = not _ctx.stack
        if self._root:
            _ctx.trace_id = _new_id()
        parent = _ctx.stack[-1] if _ctx.stack else None
        self.span_id = _new_id()
        ev = {"name": str(self.name), "span_id": self.span_id,
              "trace_id": _ctx.trace_id,
              "tname": threading.current_thread().name}
        if parent is not None:
            ev["parent_id"] = parent
        ev.update(self.attrs)
        _events.emit("span.begin", **ev)
        _ctx.stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, etype, exc, tb):
        if not self._on:
            return False
        self._on = False
        # pop OUR id even if an attach/reset raced the scope
        if _ctx.stack and _ctx.stack[-1] == self.span_id:
            _ctx.stack.pop()
        elif self.span_id in _ctx.stack:
            _ctx.stack.remove(self.span_id)
        fields = {"name": str(self.name), "span_id": self.span_id,
                  "trace_id": _ctx.trace_id,
                  "dur_us": round((time.perf_counter() - self._t0) * 1e6,
                                  1)}
        if etype is not None:
            fields["error"] = etype.__name__
        _events.emit("span.end", **fields)
        if self._root and not _ctx.stack:
            _ctx.trace_id = None
        return False


def traced(name=None, **attrs):
    """``@traced`` / ``@traced("phase", k=v)``: wrap a function in a
    :func:`span` named after it (or ``name``)."""
    import functools

    def deco(fn):
        sname = name or getattr(fn, "__name__", "span")

        @functools.wraps(fn)
        def wrapper(*a, **k):
            if not enabled():        # zero-overhead off path
                return fn(*a, **k)
            with span(sname, **attrs):
                return fn(*a, **k)
        return wrapper

    if callable(name):               # bare @traced
        fn, name = name, None
        return deco(fn)
    return deco


class RemoteTraceContext:
    """Picklable wrapper carrying a trace context alongside an rpc
    callable: the SERVER side attaches the caller's context and runs
    the call under an ``rpc.server`` span, so the remote work lands in
    the caller's trace.  Rides the existing ``(fn, args, kwargs)`` wire
    frame — the rpc protocol itself is unchanged, and with metrics off
    the client never wraps (bitwise pre-observability payloads)."""

    def __init__(self, ctx, fn):
        self.ctx = ctx
        self.fn = fn

    def __call__(self, *args, **kwargs):
        with attach(self.ctx), \
                span("rpc.server",
                     fn=getattr(self.fn, "__name__", str(self.fn)),
                     rank=trace_rank()):
            return self.fn(*args, **kwargs)


# ---------------------------------------------------------------------
# Chrome/Perfetto trace-event export
# ---------------------------------------------------------------------
# ring kinds -> export policy.  Spans fuse into "X" complete events;
# profiler span/op kinds already carry dur_us (recorded at close);
# everything else becomes a thread-scoped instant on a stable track.
_RUNTIME_KINDS = ("retry.", "guard.", "fault.", "preempt.", "flight.",
                  "compile.")


def _track_of(ev) -> str:
    kind = ev.get("kind", "")
    if kind.startswith("span.") or kind in ("span", "op"):
        return str(ev.get("tname", "main"))
    if kind.startswith("serving."):
        slot = ev.get("slot")
        return f"engine/slot{int(slot)}" if slot is not None \
            else "engine"
    for pfx in _RUNTIME_KINDS:
        if kind.startswith(pfx):
            return "runtime"
    return "events"


_META_FIELDS = ("seq", "ts", "kind", "tname")


def _args_of(ev) -> dict:
    return {k: v for k, v in ev.items() if k not in _META_FIELDS}


def render_trace(events=None, rank=None, host=None) -> dict:
    """The ring (or ``events``) as a Chrome trace-event dict.

    One Perfetto *process* per rank, one *thread* (track) per
    thread / engine slot / runtime stream; ``span.begin``/``span.end``
    pairs fuse into complete events, unmatched halves degrade to
    ``B``/``E`` phase events so a crash mid-span still renders.
    Deterministic: events sorted by (timestamp, seq), keys sorted at
    serialization — goldens pin the exact output."""
    evs = [e for e in (_events.tail() if events is None else events)
           if e is not None]
    rank = trace_rank() if rank is None else int(rank)
    host = trace_host() if host is None else str(host)
    if evs:
        base = min(float(e.get("ts", 0.0)) for e in evs)
    else:
        base = 0.0

    def us(ts):
        return round((float(ts) - base) * 1e6, 1)

    tracks: dict[str, int] = {}

    def tid(track):
        if track not in tracks:
            tracks[track] = len(tracks) + 1
        return tracks[track]

    out = []
    open_spans: dict = {}   # span_id -> (begin event, tid)
    for ev in sorted(evs, key=lambda e: (float(e.get("ts", 0.0)),
                                         e.get("seq", 0))):
        kind = ev.get("kind", "")
        if kind == "span.begin":
            open_spans[ev.get("span_id")] = (ev, tid(_track_of(ev)))
        elif kind == "span.end":
            # the END event carries no tname: the matched begin's
            # track places it; only orphans fall back to "main"
            beg = open_spans.pop(ev.get("span_id"), None)
            args = _args_of(ev)
            if beg is not None:
                bev, bt = beg
                args = dict(_args_of(bev), **args)
                dur = args.pop("dur_us", 0.0)
                args.pop("name", None)   # lifted into the event name
                out.append({"name": str(ev.get("name", "span")),
                            "cat": "span", "ph": "X",
                            "ts": us(bev.get("ts", 0.0)),
                            "dur": round(float(dur), 1),
                            "pid": rank, "tid": bt, "args": args})
            else:   # end without a begin in the ring (wrapped away)
                args.pop("name", None)
                out.append({"name": str(ev.get("name", "span")),
                            "cat": "span", "ph": "E",
                            "ts": us(ev.get("ts", 0.0)),
                            "pid": rank, "tid": tid(_track_of(ev)),
                            "args": args})
        elif kind in ("span", "op"):
            t = tid(_track_of(ev))
            # profiler events: one record at close carrying dur_us
            dur = float(ev.get("dur_us", 0.0))
            pargs = _args_of(ev)
            pargs.pop("name", None)
            pargs.pop("dur_us", None)
            out.append({"name": str(ev.get("name", kind)),
                        "cat": "profiler", "ph": "X",
                        "ts": round(us(ev.get("ts", 0.0)) - dur, 1),
                        "dur": round(dur, 1),
                        "pid": rank, "tid": t, "args": pargs})
        else:
            out.append({"name": kind, "cat": kind.split(".")[0],
                        "ph": "i", "s": "t",
                        "ts": us(ev.get("ts", 0.0)),
                        "pid": rank, "tid": tid(_track_of(ev)),
                        "args": _args_of(ev)})
    # crash-truncated spans: render the begin so the open phase shows
    for sid in sorted(open_spans, key=lambda s: (s is None, s)):
        bev, bt = open_spans[sid]
        bargs = _args_of(bev)
        bargs.pop("name", None)
        out.append({"name": str(bev.get("name", "span")),
                    "cat": "span", "ph": "B",
                    "ts": us(bev.get("ts", 0.0)),
                    "pid": rank, "tid": bt, "args": bargs})
    # complete ("X") events carry their BEGIN timestamp but were
    # appended at end-event order: one final stable sort
    out.sort(key=lambda e: (e["ts"], e["tid"], e["ph"], e["name"]))
    meta = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
             "args": {"name": f"rank{rank} ({host})"}}]
    for track in sorted(tracks, key=lambda k: tracks[k]):
        meta.append({"name": "thread_name", "ph": "M", "pid": rank,
                     "tid": tracks[track], "args": {"name": track}})
    return {"displayTimeUnit": "ms", "traceEvents": meta + out}


def export_trace(path, events=None, rank=None, host=None):
    """Write the ring (or ``events``) as a Chrome/Perfetto trace JSON
    file and return the path — or None with metrics off (no stray
    files, same contract as ``events.dump``).  Observes the export
    wall into ``trace.export_ms`` (default registry)."""
    if not enabled():
        return None
    t0 = time.perf_counter()
    rec = render_trace(events, rank=rank, host=host)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
    _registry().histogram(
        "trace.export_ms", "export_trace render+write wall",
        LATENCY_BUCKETS_MS).observe(
            (time.perf_counter() - t0) * 1e3)
    return path

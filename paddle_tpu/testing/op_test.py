"""OpTest harness — see package docstring. Reference
``test/legacy_test/op_test.py`` (OpTest :420, check_output :2765,
check_grad :2975)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import numpy as np


def _to_np(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        return np.asarray(x._read())
    return np.asarray(x)


def _flat_outputs(out):
    if isinstance(out, (list, tuple)):
        return [o for o in out if o is not None]
    return [out]


@dataclass
class OpSpec:
    """One table-driven op case.

    ``fn(*tensors, **kwargs)`` is the paddle_tpu callable; ``ref`` the
    numpy reference (same signature over ndarrays). ``inputs`` are numpy
    arrays (or shapes to fill with the default rng). ``grad`` lists input
    indices to gradient-check (empty = forward-only, e.g. integer ops)."""
    name: str
    fn: Callable
    ref: Callable
    inputs: Sequence[Any]
    kwargs: dict = field(default_factory=dict)
    grad: Sequence[int] = ()
    atol: float = 1e-5
    rtol: float = 1e-5
    bf16: bool = True
    bf16_atol: float = 2e-2
    bf16_rtol: float = 2e-2
    grad_atol: float = 5e-3
    jit: bool = True


class OpTest:
    """Programmatic harness; also usable as a mixin in hand-written tests."""

    rng = np.random.default_rng(20240730)

    # ---- forward --------------------------------------------------------
    @classmethod
    def check_output(cls, fn, ref, inputs, kwargs=None, atol=1e-5,
                     rtol=1e-5, jit=True):
        import paddle_tpu as paddle

        kwargs = kwargs or {}
        tensors = [paddle.to_tensor(np.asarray(x)) for x in inputs]
        got = _flat_outputs(fn(*tensors, **kwargs))
        want = _flat_outputs(ref(*[np.asarray(x) for x in inputs], **kwargs))
        assert len(got) == len(want), (
            f"output arity {len(got)} != reference {len(want)}")
        for g, w in zip(got, want):
            np.testing.assert_allclose(_to_np(g), np.asarray(w), atol=atol,
                                       rtol=rtol, err_msg="eager forward")
        if jit:
            static = paddle.jit.to_static(
                lambda *ts: fn(*ts, **kwargs), full_graph=True)
            got_j = _flat_outputs(static(*[paddle.to_tensor(np.asarray(x))
                                           for x in inputs]))
            for g, w in zip(got_j, want):
                np.testing.assert_allclose(
                    _to_np(g), np.asarray(w), atol=atol, rtol=rtol,
                    err_msg="jit forward")

    # ---- bfloat16 (TPU-native dtype) -----------------------------------
    @classmethod
    def check_bf16(cls, fn, ref, inputs, kwargs=None, atol=2e-2, rtol=2e-2):
        import jax.numpy as jnp

        import paddle_tpu as paddle

        kwargs = kwargs or {}
        tensors = []
        for x in inputs:
            x = np.asarray(x)
            t = paddle.to_tensor(x)
            if x.dtype == np.float32:
                t = t.astype("bfloat16")
            tensors.append(t)
        got = _flat_outputs(fn(*tensors, **kwargs))
        want = _flat_outputs(ref(*[np.asarray(x) for x in inputs], **kwargs))
        for g, w in zip(got, want):
            gv = _to_np(g.astype("float32") if hasattr(g, "astype") else g)
            np.testing.assert_allclose(gv, np.asarray(w, np.float32),
                                       atol=atol, rtol=rtol,
                                       err_msg="bf16 forward")

    # ---- gradients ------------------------------------------------------
    @classmethod
    def check_grad(cls, fn, inputs, wrt=(0,), kwargs=None, eps=1e-3,
                   atol=5e-3, rtol=5e-3):
        """Tape backward vs central-difference numeric gradient of
        ``L = sum(fn(x) * proj)`` with a fixed random projection (the
        reference's user_defined_grad_outputs pattern)."""
        import paddle_tpu as paddle

        kwargs = kwargs or {}
        inputs = [np.asarray(x) for x in inputs]
        proj = None

        def loss_np(*arrs):
            nonlocal proj
            tensors = [paddle.to_tensor(a) for a in arrs]
            out = _flat_outputs(fn(*tensors, **kwargs))
            vals = [_to_np(o).astype(np.float64) for o in out]
            if proj is None:
                proj = [cls.rng.normal(size=v.shape) for v in vals]
            return sum(float((v * p).sum()) for v, p in zip(vals, proj))

        loss_np(*inputs)  # fix proj

        # analytic grads through the tape
        tensors = []
        for i, a in enumerate(inputs):
            t = paddle.to_tensor(a)
            if i in wrt:
                t.stop_gradient = False
            tensors.append(t)
        out = _flat_outputs(fn(*tensors, **kwargs))
        loss = None
        for o, p in zip(out, proj):
            term = (o * paddle.to_tensor(p.astype(np.float32))).sum()
            loss = term if loss is None else loss + term
        loss.backward()

        for i in wrt:
            a = inputs[i]
            num = np.zeros(a.size, np.float64)
            flat = a.reshape(-1)
            for j in range(a.size):
                orig = flat[j]
                flat[j] = orig + eps
                fp = loss_np(*inputs)
                flat[j] = orig - eps
                fm = loss_np(*inputs)
                flat[j] = orig
                num[j] = (fp - fm) / (2 * eps)
            got = _to_np(tensors[i].grad).reshape(-1)
            np.testing.assert_allclose(
                got, num.astype(np.float32), atol=atol, rtol=rtol,
                err_msg=f"gradient wrt input {i}")


def run_op_specs(specs: Sequence[OpSpec]):
    """Run a table of OpSpecs, aggregating failures with op names."""
    failures = []
    for s in specs:
        try:
            OpTest.check_output(s.fn, s.ref, s.inputs, s.kwargs,
                                atol=s.atol, rtol=s.rtol, jit=s.jit)
            if s.bf16:
                OpTest.check_bf16(s.fn, s.ref, s.inputs, s.kwargs,
                                  atol=s.bf16_atol, rtol=s.bf16_rtol)
            if s.grad:
                OpTest.check_grad(s.fn, s.inputs, wrt=tuple(s.grad),
                                  kwargs=s.kwargs, atol=s.grad_atol,
                                  rtol=s.grad_atol)
        except Exception as e:  # noqa: BLE001 — aggregate, report all
            failures.append((s.name, f"{type(e).__name__}: {e}"))
    assert not failures, "op failures:\n" + "\n".join(
        f"  {n}: {m[:500]}" for n, m in failures)

"""``paddle_tpu.testing`` — the OpTest harness.

Analog of the reference's single most important test base
(``test/legacy_test/op_test.py:420``: ``check_output`` :2765 numpy-forward
comparison, ``check_grad`` :2975 numeric-vs-registered gradient, across
places and dtypes). TPU-native shape: ops are jnp-backed primitives behind
one dispatch funnel, so the harness checks (1) eager forward vs a numpy
reference, (2) the same under ``jit.to_static`` (the dygraph/static
consistency axis), (3) tape gradients vs central-difference numeric
gradients, (4) bfloat16 execution (TPU's native dtype) against the fp32
reference at loose tolerance.
"""
from .op_test import OpTest, OpSpec, run_op_specs  # noqa: F401

__all__ = ["OpTest", "OpSpec", "run_op_specs"]

"""hapi callbacks (reference ``python/paddle/hapi/callbacks.py``: Callback
:87, ProgBarLogger :263, ModelCheckpoint :517, LRScheduler :587,
EarlyStopping :673)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(_fmt(x) for x in np.ravel(v)) + "]"
    return str(v)


class ProgBarLogger(Callback):
    """Per-step/epoch console logging (reference ``callbacks.py:263``)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _line(self, step, logs):
        items = [f"step {step}" + (f"/{self.steps}" if self.steps else "")]
        for k, v in (logs or {}).items():
            items.append(f"{k}: {_fmt(v)}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and (step + 1) % self.log_freq == 0:
            print(self._line(step + 1, logs), flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(self._line(self.params.get("steps") or 0, logs) +
                  f" - {dt:.2f}s", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            print("Eval - " + " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()),
                flush=True)


class ModelCheckpoint(Callback):
    """Periodic save (reference ``callbacks.py:517``). Saves go through
    ``framework.save``, which commits atomically (temp + fsync +
    rename) — a death mid-save can no longer leave a torn
    ``<epoch>.pdparams`` that later loads as garbage. ``keep_last=K``
    garbage-collects epoch saves beyond the newest K (the ``final`` /
    ``best_model`` saves are never collected)."""

    def __init__(self, save_freq=1, save_dir=None, keep_last=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.keep_last = None if keep_last is None else max(1, keep_last)
        self._saved = []

    def on_train_begin(self, logs=None):
        # seed GC state from disk: after a preemption restart (or a
        # second fit on this Model) the previous attempt's epoch saves
        # must count toward keep_last, or the directory grows without
        # bound across restarts
        if not (self.save_dir and self.keep_last is not None):
            return
        try:
            names = os.listdir(self.save_dir)
        except OSError:
            names = []
        self._saved = sorted({
            int(f.rsplit(".", 1)[0]) for f in names
            if f.endswith((".pdparams", ".pdopt"))
            and f.rsplit(".", 1)[0].isdigit()})

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)
            if self.keep_last is None:
                return
            if epoch in self._saved:  # resumed run re-saving an epoch
                self._saved.remove(epoch)
            self._saved.append(epoch)
            while len(self._saved) > self.keep_last:
                old = self._saved.pop(0)
                for suffix in (".pdparams", ".pdopt"):
                    try:
                        os.remove(os.path.join(self.save_dir,
                                               str(old) + suffix))
                    except OSError:
                        pass

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference ``callbacks.py:587``)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    ``callbacks.py:673``)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_eval_end(self, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        value = np.ravel(value)[0]
        if self.best is None or self.monitor_op(value - self.min_delta,
                                                self.best):
            self.best = value
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not improve "
                          f"for {self.patience} evals")


def config_callbacks(callbacks, model, epochs=None, steps=None, verbose=2,
                     log_freq=10, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "save_dir": save_dir})
    return lst

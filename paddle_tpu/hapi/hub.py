"""Model hub (reference ``python/paddle/hapi/hub.py``:174,220,263).

``source='local'`` loads entrypoints from a ``hubconf.py`` in a local
directory — fully supported. Remote sources (github/gitee) require network
egress, which this runtime does not have; they raise with a clear message
instead of hanging on a download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ['list', 'help', 'load', 'download']

MODULE_HUBCONF = 'hubconf.py'
VAR_DEPENDENCY = 'dependencies'


def download(url, dst, fetcher=None, max_attempts=4):
    """Fetch ``url`` into ``dst`` atomically, retrying transient
    failures with exponential backoff (``resilience.retry``) — flaky
    object stores are the rule, not the exception, at fleet scale.

    ``fetcher(url) -> bytes`` defaults to urllib (this runtime has no
    egress, so pass your own for air-gapped mirrors and in tests). The
    write commits through ``resilience.atomic_write``: a crash
    mid-download never leaves a half file under ``dst``.
    """
    from ..resilience import faults
    from ..resilience.atomic import atomic_write
    from ..resilience.retry import retry_call

    if fetcher is None:
        def fetcher(u):
            from urllib.request import urlopen
            with urlopen(u) as r:
                return r.read()

    def attempt():
        faults.maybe_raise("download_transient", os.path.basename(dst))
        return fetcher(url)

    def permanent(e):
        # urllib's HTTPError subclasses OSError; a 4xx is not transient
        code = getattr(e, "code", None)
        return code is not None and 400 <= int(code) < 500

    # http.client.HTTPException covers mid-body drops (IncompleteRead,
    # chunked-encoding errors) that are NOT OSError subclasses but are
    # exactly the flaky-store failures worth retrying
    import http.client
    data = retry_call(attempt, max_attempts=max_attempts,
                      retry_on=(OSError, http.client.HTTPException),
                      giveup=permanent)
    with atomic_write(dst) as f:
        f.write(data)
    return dst


def _import_module(name, repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    # namespaced so a repo dir called e.g. "models" can't shadow real modules
    name = f"paddle_tpu_hubconf.{name}"
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


def _get_module(repo_dir, source, force_reload=False):
    if source not in ('github', 'gitee', 'local'):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            f'"gitee" | "local".')
    if source != 'local':
        raise RuntimeError(
            f'source="{source}" needs network access, which this runtime '
            'does not have; clone the repo and use source="local".')
    return _import_module(os.path.basename(repo_dir), repo_dir)


def _check_dependencies(m):
    deps = getattr(m, VAR_DEPENDENCY, None)
    if deps:
        missing = [p for p in deps if importlib.util.find_spec(p) is None]
        if missing:
            raise RuntimeError(f'Missing dependencies: {missing}')


def _load_entry_from_hubconf(m, name):
    if not isinstance(name, str):
        raise ValueError('Invalid input: model should be a str of function '
                         'name')
    entry = getattr(m, name, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f'Cannot find callable {name} in hubconf')
    return entry


def list(repo_dir, source='github', force_reload=False):
    """All public callables defined by the repo's hubconf.py."""
    m = _get_module(repo_dir, source, force_reload)
    return [f for f in dir(m)
            if callable(getattr(m, f)) and not f.startswith('_')]


def help(repo_dir, model, source='github', force_reload=False):
    """Docstring of one hub entrypoint."""
    m = _get_module(repo_dir, source, force_reload)
    return _load_entry_from_hubconf(m, model).__doc__


def load(repo_dir, model, source='github', force_reload=False, **kwargs):
    """Instantiate a hub entrypoint: ``entry(**kwargs)``."""
    m = _get_module(repo_dir, source, force_reload)
    _check_dependencies(m)
    return _load_entry_from_hubconf(m, model)(**kwargs)

"""hapi ``Model`` — the Keras-like high-level train/eval/predict engine.

Capability analog of ``python/paddle/hapi/model.py`` (Model :872, fit
:1052, evaluate :1287, predict :1391, train_batch :944, save/load
:1472,1560, prepare :1019). TPU-native twist: the per-batch train and eval
steps are compiled whole via ``jit.to_static`` on first use, so the fit
loop dispatches one fused XLA program per batch instead of per-op work —
the hapi analog of the reference's dygraph-to-static acceleration, on by
default because eager dispatch over a TPU link is the slow path.
"""
from __future__ import annotations

import os
import signal
import time

import numpy as np

from .. import optimizer as opt_mod
from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _to_tensors(batch):
    out = []
    for b in _to_list(batch):
        if isinstance(b, Tensor):
            out.append(b)
        else:
            out.append(Tensor(np.asarray(b)))
    return out


def _prefetch_metrics():
    from ..observability import metrics as m
    if not m.enabled():
        return None
    reg = m.registry()
    return (
        reg.histogram("train.input_wait_ms",
                      "time the train loop blocked waiting for the next "
                      "batch to stage (a prefetch miss pays the full "
                      "host->device stage)", m.LATENCY_BUCKETS_MS),
        reg.gauge("train.input_overlap_frac",
                  "fraction of input staging time overlapped with "
                  "in-flight train steps (this fit so far)"),
    )


class _PrefetchFeed:
    """Double-buffered host->device input staging (ISSUE 19).

    Wraps a loader so the fit loops consume pre-staged ``(step, inputs,
    labels)`` triples: while step N's compiled program is in flight
    (dispatched but not yet read back), ``advance()`` — installed as
    ``Model._prefetch_hook`` and fired from ``train_batch`` between the
    async dispatch and the blocking ``float(loss)`` — pulls batch N+1
    from the loader, splits it, and stages it to device. The loop's
    next ``__next__`` then serves the staged batch with ~zero wait.

    Staging is exactly the synchronous path's ``_split_batch`` +
    ``_to_tensors`` on the same batches in the same order — only WHEN
    the host does the work moves, so the loss trajectory is bitwise
    identical to ``train_prefetch=off`` (asserted in
    tests/test_train_perf.py). Misses (first batch of an epoch, a
    loader slower than the step) fall back to an in-line synchronous
    fetch and show up in ``train.input_wait_ms``;
    ``train.input_overlap_frac`` tracks how much staging time hid
    behind device execution.
    """

    def __init__(self, loader, split, skip=0, enabled=True):
        self._it = iter(loader)
        self._split = split
        self._skip = int(skip)
        self._step = 0
        self._staged = None
        self._done = False
        self.enabled = bool(enabled)
        self.wait_ms = 0.0
        self.overlap_ms = 0.0
        self._handles = _prefetch_metrics()

    def _fetch(self):
        while self._skip > 0:  # resume fast-forward: never staged
            self._skip -= 1
            self._step += 1
            next(self._it)
        batch = next(self._it)
        inputs, labels = self._split(batch)
        return _to_tensors(inputs), _to_tensors(labels)

    def _gauge(self):
        if self._handles is None:
            return
        total = self.wait_ms + self.overlap_ms
        self._handles[1].set(self.overlap_ms / total if total else 0.0)

    def advance(self):
        """Stage the next batch while the current step is in flight."""
        if self._done or self._staged is not None:
            return
        t0 = time.perf_counter()
        try:
            self._staged = self._fetch()
        except StopIteration:
            self._done = True
            return
        self.overlap_ms += (time.perf_counter() - t0) * 1000.0
        self._gauge()

    def __iter__(self):
        return self

    def __next__(self):
        if self._staged is not None:
            pair, self._staged = self._staged, None
            wait = 0.0
        else:
            if self._done:
                raise StopIteration
            t0 = time.perf_counter()
            pair = self._fetch()  # miss: pay the stage in-line
            wait = (time.perf_counter() - t0) * 1000.0
        self.wait_ms += wait
        if self._handles is not None:
            self._handles[0].observe(wait)
        self._gauge()
        step, self._step = self._step, self._step + 1
        return step, pair[0], pair[1]


class Model:
    """High-level model wrapper: ``prepare`` -> ``fit``/``evaluate``/
    ``predict`` (reference ``hapi/model.py:872``)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._train_step = None
        self._train_step_noupd = None
        self._eval_step = None
        self._accumulate = 1
        self._step_guard = None
        self._preempted = False
        self._preempt_position = None
        self._prefetch_hook = None

    # -- setup ---------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, step_guard=None, remat=None):
        """``step_guard`` (TPU extension): a ``resilience.StepGuard`` —
        or ``True`` for the defaults — makes every non-finite train
        step a bitwise no-op inside the compiled step and raises a
        coded ``NonFiniteStepError`` only after the guard's
        consecutive-bad-step budget is spent.

        ``remat`` (TPU extension, ISSUE 19): selective activation
        rematerialization for the compiled train step. ``True`` (or the
        ``train_remat`` flag set to an on-spelling) selects the
        ``dots_and_kernels_saveable`` policy — matmul and Pallas-kernel
        outputs (flash attention) stay saved, cheap elementwise/norm
        glue is recomputed in the backward pass; any
        ``fleet.recompute`` policy name selects that policy. The saving
        is peak-HBM only: grads are BITWISE identical remat on/off
        (recompute replays the same ops on the same values), proven in
        tests/test_train_perf.py and measurable via the captured step's
        ``static_peak_bytes``. ``None`` defers to the ``train_remat``
        flag; ``False``/"" disables."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle.metric.Metric, "
                                f"got {type(m).__name__}")
        if step_guard is True:
            from ..resilience import StepGuard
            step_guard = StepGuard()
        self._step_guard = step_guard or None
        policy = self._resolve_remat(remat)
        if policy is not None:
            self._apply_remat(policy)
        self._amp_level = None
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        self._build_steps()
        self._lint_network()
        return self

    def _resolve_remat(self, remat):
        """Normalize ``prepare(remat=)`` / the ``train_remat`` flag to a
        ``fleet.recompute`` policy name, or None for off."""
        from ..core import state as _state
        from ..distributed.fleet.recompute import _POLICIES
        if remat is None:
            remat = _state.get_flag("train_remat")
        if remat is None or remat is False or remat == "":
            return None
        if remat is True:
            return "dots_and_kernels_saveable"
        name = str(remat).strip().lower()
        if name in _state.KV_QUANT_ON_SPELLINGS:
            return "dots_and_kernels_saveable"
        if name in _state.KV_QUANT_OFF_SPELLINGS:
            return None
        if name not in _POLICIES or name == "none":
            raise ValueError(
                f"prepare(remat={remat!r}): unknown remat policy; "
                f"expected one of "
                f"{sorted(k for k in _POLICIES if isinstance(k, str))} "
                f"or an on/off spelling")
        return name

    def _apply_remat(self, policy):
        """Flip every remat-capable block of the network (any sublayer
        carrying the ``_recompute`` attr — GPTBlock, LlamaDecoderLayer,
        BertLayer, and user blocks following the same convention) to
        recompute with ``policy``. 'full' maps to the policy-less
        jax.checkpoint (save nothing but inputs)."""
        pol = None if policy == "full" else policy
        n = 0
        for layer in self.network.sublayers(include_self=True):
            if not hasattr(layer, "_recompute"):
                continue
            layer._recompute = True
            # the block families disagree on the policy attr name
            # (GPTBlock: _recompute_policy; llama/bert: _policy) —
            # set whichever the block defines
            for attr in ("_recompute_policy", "_policy"):
                if hasattr(layer, attr):
                    setattr(layer, attr, pol)
            n += 1
        if n == 0:
            import warnings
            warnings.warn(
                "prepare(remat=...): no remat-capable blocks found "
                "(no sublayer defines _recompute) — remat is a no-op "
                "for this network", RuntimeWarning)

    def _lint_network(self):
        """Pre-compile tracer-safety lint (graph lint, PDT1xx) over the
        user network's ``forward`` — the code the compiled train/eval
        steps will trace. Framework-provided layers are exempt; gated by
        PDTPU_ANALYSIS (raises under =error, no-op under =off)."""
        from .. import analysis
        fwd = getattr(type(self.network), "forward", None)
        if fwd is None:
            return
        mod = getattr(fwd, "__module__", "") or ""
        if mod == "paddle_tpu" or mod.startswith("paddle_tpu."):
            return
        analysis.lint_callable(
            fwd, where=f"{type(self.network).__name__}.forward")

    def _build_steps(self):
        from .. import amp as amp_mod
        from .. import jit

        net, loss_fn, opt = self.network, self._loss, self._optimizer
        level = self._amp_level
        guard = self._step_guard

        accum = self._accumulate

        # metrics need the per-step network outputs; without metrics the
        # outputs slot returns the loss instead — a windowed run would
        # otherwise stack K copies of the raw outputs on device (K x
        # [B,S,V] logits for an LM is tens of GB)
        has_metrics = bool(self._metrics)

        def make_train_step(update):
            def train_step(*batch_args):
                n_label = len(_to_list(self._labels)) or 1
                inputs, labels = batch_args[:-n_label], batch_args[-n_label:]
                if level:
                    with amp_mod.auto_cast(level=level, dtype="bfloat16"):
                        outputs = net(*inputs)
                        loss = loss_fn(outputs, *labels)
                else:
                    outputs = net(*inputs)
                    loss = loss_fn(outputs, *labels)
                (loss / accum if accum > 1 else loss).backward()
                if update:
                    if guard is not None:
                        # in-graph non-finite skip (resilience.StepGuard)
                        guard.guarded_step(opt, loss)
                    else:
                        opt.step()
                    # accum mode zeroes in place: grad buffers keep their
                    # identity so the compiled steps thread them as state
                    opt.clear_grad(set_to_zero=accum > 1)
                return loss, (outputs if has_metrics else loss)
            return train_step

        def eval_step(*batch_args):
            n_label = len(_to_list(self._labels)) or 1
            inputs, labels = batch_args[:-n_label], batch_args[-n_label:]
            outputs = net(*inputs)
            loss = loss_fn(outputs, *labels) if loss_fn is not None else None
            return loss, outputs

        # whole-step compilation (graph breaks fall back to eager)
        self._train_step = jit.to_static(make_train_step(True))
        self._train_step_noupd = jit.to_static(make_train_step(False))
        self._eval_step = jit.to_static(eval_step)

    def _reset_compiled_steps(self):
        """Drop the cached compiled train/eval programs (ISSUE 15:
        called by ``resilience.FleetSupervisor`` after an external
        state restore).  A captured step holds its state tensors BY
        IDENTITY — with the fused optimizer that is the flat dtype
        buckets, and ``Optimizer.set_state_dict`` dissolves those
        buckets ("they rebuild at the next step()" — but a CAPTURED
        step never runs eagerly again, so a cached program would keep
        training the orphaned bucket storage while the restored
        per-param tensors sit frozen).  Clearing the caches makes the
        first post-restore batch re-discover: buckets rebuild from the
        restored values and a fresh program captures them."""
        for fn in (self._train_step, self._train_step_noupd,
                   self._eval_step):
            if fn is None:
                continue
            for attr in ("_cache", "_fallback_keys", "_fallback_counts"):
                c = getattr(fn, attr, None)
                if c is not None:
                    c.clear()

    # -- batch-level API (reference :944,:975,:1002) -------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        label_ts = _to_tensors(labels)
        args = _to_tensors(inputs) + label_ts
        step_fn = self._train_step if update else self._train_step_noupd
        if self._accumulate > 1:
            # Seed zero grads so the compiled step always sees existing
            # grads — keeps op structure deterministic across calls
            # (backward would otherwise *create* grads on the first call
            # after clear_grad and *accumulate* on later ones, which the
            # jit capture rejects as a graph break).
            from ..ops.creation import zeros_like
            for p in self.network.parameters():
                if not p.stop_gradient and p.grad is None:
                    p.grad = zeros_like(p)
        loss, outputs = step_fn(*args)
        # the step is dispatched (device-side, async) but not yet read
        # back: the window between here and float(loss) is where input
        # prefetch hides the next batch's host->device stage (ISSUE 19)
        hook = self._prefetch_hook
        if hook is not None:
            hook()
        loss_val = float(loss)
        if self._step_guard is not None and update:
            self._step_guard.observe(loss_val)
        metrics = self._update_metrics(outputs, label_ts)
        return [loss_val] + metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        label_ts = _to_tensors(labels)
        args = _to_tensors(inputs) + label_ts
        loss, outputs = self._eval_step(*args)
        metrics = self._update_metrics(outputs, label_ts)
        return ([float(loss)] if loss is not None else []) + metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.autograd import no_grad
        with no_grad():
            out = self.network(*_to_tensors(inputs))
        return out

    def _update_metrics(self, outputs, labels):
        vals = []
        out0 = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        for m in self._metrics:
            res = m.compute(out0, *labels)
            vals.append(m.update(*_to_list(res)) if not isinstance(res, tuple)
                        else m.update(*res))
        return vals

    # -- loops (reference fit :1052) -----------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        raise TypeError("data must be a Dataset or DataLoader")

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, window=1,
            resume=False, keep_last_k=3):
        """``window=K`` (TPU extension over the reference fit signature,
        ``hapi/model.py:1052``): dispatch K train steps as ONE compiled
        scan launch (``jit.WindowRunner``) with inputs pre-staged on
        device and per-step scheduler LRs threaded through the window
        (``optimizer.lr_window``). Per-step host dispatch over a
        network-attached chip otherwise dominates the step time; see
        BASELINE.md. Callbacks and metrics observe every step, after
        its window completes; epoch tails shorter than K (and
        ``accumulate_grad_batches > 1`` runs) use the per-batch path.

        Resilience (TPU extension, ``paddle_tpu.resilience``): with a
        ``save_dir``, fit keeps ``keep_last_k`` versioned checkpoints
        (``save_dir/step_<N>``, atomic + COMPLETE-marked) — one per
        ``save_freq`` epochs — alongside the reference-parity
        ``<epoch>.pdparams`` saves (kept unbounded, as before; pass
        ``ModelCheckpoint(..., keep_last=K)`` to bound those too), and
        installs a SIGTERM/SIGINT handler
        that checkpoints the exact position at the next step boundary
        and exits the loops cleanly (preemption). ``resume=True``
        restores model/optimizer/RNG from the newest COMPLETE version
        (torn versions are skipped automatically) and continues from
        the recorded epoch/step; with no checkpoint yet it trains from
        scratch, so the same launch command works for attempt #1 and
        every restart. ``resume=(epoch, steps_done, global_step)``
        (ISSUE 15) is the in-memory variant: no disk restore happens —
        the caller (``resilience.FleetSupervisor`` after a buddy-
        snapshot restore) already placed the state and fit just starts
        from that position."""
        assert self._optimizer is not None, "call prepare() before fit()"
        if accumulate_grad_batches != self._accumulate:
            self._accumulate = accumulate_grad_batches
            self._build_steps()
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last)
        steps = len(loader) if hasattr(loader, "__len__") else None
        # NOTE: keep_last_k bounds only the resilience versions
        # (step_<N> dirs); the reference-parity <epoch>.pdparams saves
        # keep ALL epochs as before — deleting user checkpoints can't
        # be a default. Opt in with callbacks=[ModelCheckpoint(
        # save_freq, save_dir, keep_last=K)].
        cbks = config_callbacks(callbacks, self, epochs=epochs, steps=steps,
                                verbose=verbose, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=self._metrics_name())
        from ..resilience import preempt as _preempt
        from ..resilience.checkpoint import CheckpointManager
        mgr = (CheckpointManager(save_dir, keep_last_k=keep_last_k)
               if save_dir else None)
        start_epoch, skip_steps, it = 0, 0, 0
        self._preempted = False
        if isinstance(resume, (tuple, list)):
            # in-memory resume (resilience.elastic_train
            # FleetSupervisor): state restoration already happened
            # host-side (buddy snapshot / disk fallback applied by the
            # supervisor); fit only takes the position — (start_epoch,
            # steps already done in that epoch, global step) — with no
            # checkpoint directory involved
            start_epoch, skip_steps, it = (int(v) for v in resume)
        elif resume:
            if mgr is None:
                raise ValueError("fit(resume=True) requires save_dir")
            pos = self._restore_resilient(mgr)
            if pos is not None:
                start_epoch, skip_steps, it = pos
                if skip_steps and shuffle and not isinstance(train_data,
                                                             DataLoader):
                    import warnings
                    warnings.warn(
                        "fit(resume=True) is fast-forwarding "
                        f"{skip_steps} steps into an epoch, but "
                        "shuffle=True rebuilds the batch order from "
                        "scratch — the skipped prefix is not exactly "
                        "the already-trained prefix (some samples "
                        "repeat, others drop this epoch). Pass "
                        "shuffle=False or a deterministically-ordered "
                        "DataLoader for exact mid-epoch resume.",
                        RuntimeWarning)
        installed = False
        if mgr is not None:
            # only clear/uninstall state this fit OWNS: inside a user's
            # own preempt.install() scope, a pending request stays
            # pending (it is honored at the first step boundary) and
            # the user's handler survives fit
            installed = _preempt.install()
            if installed:
                _preempt.clear()
        self.stop_training = False
        # training step telemetry (ISSUE 8, observability.StepTimer):
        # step wall-time histogram, tokens/sec + MFU gauges, and a
        # retrace counter over the compiled train step — recorded into
        # the process-global registry; near-no-op with PDTPU_METRICS=off
        from ..observability import StepTimer
        from ..observability import metrics as _obs_metrics
        from ..observability import watchdog as _watchdog
        self._step_timer = StepTimer(n_params=sum(
            int(np.prod([int(s) for s in p.shape]) or 1)
            for p in self.network.parameters()))
        # stall watchdog (ISSUE 14): with the watchdog_stall_ms flag
        # set, this fit is armed and each completed step heartbeats it
        # at the SAME sites the StepTimer records — a training loop
        # wedged past the deadline (hung collective, dead tunnel)
        # gets thread stacks + a flight record instead of silence.
        # Size the deadline to cover eval/checkpoint gaps and (for
        # fit(window=K)) one whole scanned window.  No interrupt: a
        # mid-step injection could corrupt optimizer state.
        from ..core import state as _core_state
        self._fit_watchdog = _watchdog.arm(
            "train.step",
            float(_core_state.get_flag("watchdog_stall_ms")),
            key="fit")
        if _obs_metrics.enabled():
            # HBM accounting (ISSUE 12): resident parameter bytes of
            # the network this fit trains, read LAZILY at snapshot time
            # (weakref: the gauge must not keep a finished fit's model
            # alive); joins jit's hbm.program_state_bytes /
            # hbm.live_bytes series
            import weakref as _weakref
            _net = _weakref.ref(self.network)

            def _model_bytes(_net=_net):
                net = _net()
                if net is None:
                    return 0
                return int(sum(
                    int(getattr(getattr(p, "_data", None), "nbytes", 0)
                        or 0) for p in net.parameters()))

            _obs_metrics.registry().gauge(
                "hbm.model_param_bytes",
                "parameter bytes of the network under fit (lazy)"
            ).set_function(_model_bytes)
        try:
            cbks.on_train_begin()
            logs = {}
            wstate = {"runner": None}  # WindowRunner reused across epochs
            self._window_fallback_warned = False  # warn once per fit
            for epoch in range(start_epoch, epochs):
                cbks.on_epoch_begin(epoch)
                # re-arm the step clock: the gap since last epoch's end
                # (eval pass, checkpoint write) is not a train step
                self._step_timer.mark()
                self._fit_watchdog.heartbeat()
                for m in self._metrics:
                    m.reset()
                logs = {}
                skip = skip_steps if epoch == start_epoch else 0
                if window > 1 and self._accumulate == 1:
                    logs, it = self._run_windowed_epoch(
                        loader, cbks, window, it, num_iters, wstate,
                        skip=skip, epoch=epoch, mgr=mgr)
                else:
                    feed = _PrefetchFeed(
                        loader, self._split_batch, skip=skip,
                        enabled=bool(
                            _core_state.get_flag("train_prefetch")))
                    self._prefetch_hook = (feed.advance if feed.enabled
                                           else None)
                    warmed = False
                    try:
                        for step, inputs, labels in feed:
                            if not warmed:
                                # the first fetch is the double-buffer
                                # warm-up fill (synchronous by nature):
                                # re-mark so it isn't billed to step
                                # 0's train.step_ms (ISSUE 19)
                                self._step_timer.mark()
                                warmed = True
                            cbks.on_train_batch_begin(step)
                            inputs = self._maybe_poison(inputs, it + 1)
                            update = ((step + 1) % self._accumulate == 0
                                      or (steps is not None
                                          and step + 1 == steps))
                            res = self.train_batch(inputs, labels,
                                                   update=update)
                            logs = self._make_logs(res)
                            cbks.on_train_batch_end(step, logs)
                            self._note_train_step(inputs)
                            it += 1
                            if update:
                                if self._maybe_preempt(
                                        mgr, epoch, step + 1, it,
                                        epoch_steps=steps):
                                    break
                            else:
                                # mid-accumulation: the partially summed
                                # grads are not checkpointable, so only
                                # deliver the synthetic signal here —
                                # the request is honored (checkpoint +
                                # exit) at the next update boundary
                                self._fire_synthetic_preempt(mgr, it)
                            if (num_iters is not None
                                    and it >= num_iters):
                                self.stop_training = True
                                break
                    finally:
                        self._prefetch_hook = None
                if self._preempted:
                    # exit fast — the position is already checkpointed.
                    # The epoch-boundary callbacks (ModelCheckpoint's
                    # '<epoch>' save among them) only run if the epoch
                    # actually completed; eval is always skipped — a
                    # real preemption grace period doesn't fit an eval
                    # pass
                    if self._preempt_position[0] > epoch:
                        cbks.on_epoch_end(epoch, logs)
                    break
                cbks.on_epoch_end(epoch, logs)
                # no epoch-boundary save when the epoch was cut short
                # (num_iters / a callback setting stop_training): its
                # (epoch+1, 0) position would lie, and resume would
                # silently skip the untrained remainder of the epoch.
                # EarlyStopping is unaffected — it stops from the eval
                # below, after the completed epoch's save.
                if (mgr is not None and not self.stop_training
                        and (epoch + 1) % save_freq == 0):
                    self._resilient_save(mgr, epoch + 1, 0, it)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size,
                                  num_workers=num_workers, verbose=0,
                                  callbacks=cbks)
                if self.stop_training:
                    break
            if not self._preempted:
                # a preempted fit exits without the train-end callbacks:
                # ModelCheckpoint.on_train_end would label half-trained
                # weights 'final', and the extra save eats grace period
                cbks.on_train_end(logs)
        finally:
            # clean runs leave nothing armed: the fit's watchdog entry
            # dies with the fit, success or not
            self._fit_watchdog.disarm()
            interrupted = False
            if installed:
                interrupted = (self._preempted and
                               _preempt.last_signal() == signal.SIGINT)
                _preempt.uninstall()
                # fit owned the handler and honored (or outlived) the
                # request — a stale sticky flag would make the next
                # install()-scope in this process spuriously "preempt"
                # with no signal delivered
                _preempt.clear()
        if interrupted:
            # Ctrl-C keeps its abort semantics for existing callers:
            # the position is checkpointed (resume-able), then the
            # interrupt propagates instead of fit returning "success"
            # into code that would treat the half-trained model as done
            raise KeyboardInterrupt

    def _run_windowed_epoch(self, loader, cbks, window, it, num_iters,
                            wstate, skip=0, epoch=0, mgr=None):
        """One epoch with K-step scanned windows (see ``fit(window=)``).
        The first batch runs per-batch (it is also the compile trigger);
        full windows then go through ONE WindowRunner launch each, with
        the scheduler advanced via ``lr_window``. Epoch tails and any
        fallback (step not compiled, LR slot not threadable) use the
        per-batch path. ``skip`` resume-fast-forwards that many leading
        batches; preemption is honored at step boundaries (window
        flushes observe it after the window completes)."""
        from .. import jit
        from ..core import state as _core_state

        logs, step = {}, int(skip)
        esteps = len(loader) if hasattr(loader, "__len__") else None

        def plain(inputs, labels):
            nonlocal logs, step, it
            cbks.on_train_batch_begin(step)
            inputs = self._maybe_poison(inputs, it + 1)
            res = self.train_batch(inputs, labels)
            logs = self._make_logs(res)
            cbks.on_train_batch_end(step, logs)
            self._note_train_step(inputs)
            step += 1
            it += 1
            self._maybe_preempt(mgr, epoch, step, it, epoch_steps=esteps)

        def peek_lrs():
            """Next K per-step LRs WITHOUT advancing the scheduler: the
            auto-configured LRScheduler callback owns the advance (it
            fires per batch-end below; lr_window would double-step).
            With epoch-granular scheduling the in-window LR is constant."""
            from ..optimizer.lr import LRScheduler as Sched
            from .callbacks import LRScheduler as LRCb
            sched = getattr(self._optimizer, "_learning_rate", None)
            if not isinstance(sched, Sched):
                return np.full((window,), float(sched), np.float32)
            stepped = any(isinstance(c, LRCb) and c.by_step
                          for c in getattr(cbks, "callbacks", []))
            if not stepped:
                return np.full((window,), float(sched()), np.float32)
            snap = sched.state_dict()
            vals = self._optimizer.lr_window(window)
            sched.set_state_dict(snap)
            return vals

        def flush_window(buf):
            nonlocal logs, step, it
            runner = wstate["runner"]
            # poison at EXECUTION time (step k of this window runs as
            # global step it+k+1) so a fault-spec occurrence is counted
            # exactly once per executed step, same as the per-batch
            # path, and never consumed by a batch that gets discarded
            poisoned = [(self._maybe_poison(i, it + k + 1), l)
                        for k, (i, l) in enumerate(buf)]
            batches = [tuple(_to_tensors(i) + _to_tensors(l))
                       for i, l in poisoned]
            label_lists = [_to_tensors(l) for _, l in poisoned]
            self.network.train()
            stacks = runner.stage(batches)
            ps = [peek_lrs()] if wstate.get("lr_slot") else None
            rets = runner.run(*stacks, outputs="stacked",
                              per_step_vals=ps)
            # the window is dispatched but not yet read back: stage the
            # next batch under the K in-flight steps (ISSUE 19)
            hook = self._prefetch_hook
            if hook is not None:
                hook()
            for k, (loss, outputs) in enumerate(
                    runner.rebuild_host(rets)):
                cbks.on_train_batch_begin(step)
                loss_val = float(loss)
                if self._step_guard is not None:
                    self._step_guard.observe(loss_val)
                metrics = self._update_metrics(outputs, label_lists[k])
                logs = self._make_logs([loss_val] + metrics)
                cbks.on_train_batch_end(step, logs)
                self._note_train_step(poisoned[k][0])
                step += 1
                it += 1
                # synthetic preemption keyed on each step's number still
                # fires, but the checkpoint waits for the window end:
                # the whole window's updates are ALREADY applied on
                # device, so a mid-window position would disagree with
                # the saved weights and resume would replay applied
                # steps
                self._fire_synthetic_preempt(mgr, it)
            self._maybe_preempt(mgr, epoch, step, it, epoch_steps=esteps,
                                fire=False)

        feed = _PrefetchFeed(
            loader, self._split_batch, skip=skip,
            enabled=bool(_core_state.get_flag("train_prefetch")))
        self._prefetch_hook = feed.advance if feed.enabled else None
        warmed = False
        buf = []
        try:
            for _, inputs, labels in feed:
                if not warmed:
                    # double-buffer warm-up fill: not step 1's time
                    self._step_timer.mark()
                    warmed = True
                if self.stop_training or (num_iters is not None
                                          and it >= num_iters):
                    self.stop_training = True
                    break
                if wstate["runner"] is None:
                    plain(inputs, labels)  # compile trigger + step 1
                    wstate["runner"] = self._make_window_runner(
                        inputs, labels, window, wstate)
                    continue
                if wstate["runner"] is False:
                    plain(inputs, labels)
                    continue
                buf.append((inputs, labels))
                room = (num_iters - it if num_iters is not None
                        else None)
                if room is not None and room < window:
                    # budget smaller than a window: finish per-batch
                    # (the top-of-loop check stops at num_iters
                    # exactly); without this the loop would buffer the
                    # whole remaining epoch
                    for i2, l2 in buf:
                        if self.stop_training or it >= num_iters:
                            break
                        plain(i2, l2)
                    buf = []
                    continue
                if len(buf) == window:
                    flush_window(buf)
                    buf = []
            for inputs, labels in buf:  # epoch tail / num_iters remnant
                if self.stop_training:
                    break  # preempted: the checkpoint position is final
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
                plain(inputs, labels)
        finally:
            self._prefetch_hook = None
        if num_iters is not None and it >= num_iters:
            self.stop_training = True
        return logs, it

    def _make_window_runner(self, inputs, labels, window, wstate):
        """Build the WindowRunner AFTER the first per-batch step proved
        the step compiles. Returns the runner, or False for the
        per-batch path. Never executes a training step itself: a
        WindowRunner constructed against an uncompiled step would prime
        by running one real step (extra optimizer updates on batch 1 —
        silent trajectory corruption when construction then fails)."""
        from .. import jit
        from ..optimizer.lr import LRScheduler as Sched

        sf = self._train_step
        sf = sf if hasattr(sf, "_cache") else getattr(
            sf, "__wrapped__", sf)
        if getattr(sf, "_fallback_keys", None) or \
                not getattr(sf, "_cache", None):
            # graph break: stay per-batch
            sites = sorted(getattr(sf, "_fallback_keys", None) or [])
            return self._window_fallback(
                window, "the train step graph-breaks"
                + (f" at {sites}" if sites else " (no compiled step)"))
        ex = tuple(_to_tensors(inputs) + _to_tensors(labels))
        try:
            runner = jit.WindowRunner(
                self._train_step, ex, length=window,
                per_step=[self._optimizer.lr_var])
            wstate["lr_slot"] = True
            return runner
        except Exception as e:
            per_step_reason = f"{type(e).__name__}: {e}"
        if isinstance(getattr(self._optimizer, "_learning_rate", None),
                      Sched):
            # LR cannot thread per-step and a by-step scheduler is
            # active: windowing would freeze the LR at window-start
            # values — per-batch keeps the documented trajectory
            return self._window_fallback(
                window, "the LR slot could not thread per-step "
                f"({per_step_reason}) and a by-step LR scheduler is "
                "active — windowing would freeze the LR at "
                "window-start values")
        try:
            runner = jit.WindowRunner(self._train_step, ex,
                                      length=window)
            wstate["lr_slot"] = False
            return runner
        except Exception as e:
            return self._window_fallback(
                window, f"WindowRunner construction failed: "
                f"{type(e).__name__}: {e}")

    def _window_fallback(self, window, reason):
        """Degrading to per-batch dispatch is the right default; doing
        it SILENTLY is not (VERDICT r5 weak 6) — warn once per fit."""
        import warnings
        if not getattr(self, "_window_fallback_warned", False):
            self._window_fallback_warned = True
            warnings.warn(
                f"fit(window={window}): falling back to per-batch "
                f"dispatch ({reason}); throughput will be the "
                "per-batch path's", RuntimeWarning, stacklevel=3)
        return False

    # -- observability (step telemetry) --------------------------------
    def _train_trace_count(self):
        """Total XLA (re)traces of the compiled train step — the
        StepTimer turns increases past the first compile into the
        ``train.retraces`` counter (a steady-state increment is the
        shape/state-churn regression the jit guards warn about)."""
        sf = self._train_step
        sf = sf if hasattr(sf, "_cache") else getattr(
            sf, "__wrapped__", sf)
        cache = getattr(sf, "_cache", None) or {}
        return sum(getattr(e, "trace_count", 0)
                   for e in cache.values())

    def _note_train_step(self, inputs):
        """One completed train step for the StepTimer: tokens from the
        first input's element count (batch x seq for an LM — the
        standard throughput denominator), retraces from the compiled
        step. Near-no-op when PDTPU_METRICS=off."""
        st = getattr(self, "_step_timer", None)
        if st is None:
            return
        # one completed step = one watchdog heartbeat (the null token
        # makes this a no-op attribute call when the watchdog is off
        # or metrics are off — today's behavior bitwise)
        wd = getattr(self, "_fit_watchdog", None)
        if wd is not None:
            wd.heartbeat()
        from ..observability import metrics as _obs_metrics
        if not _obs_metrics.enabled():
            # honor the flag's near-no-op contract BEFORE the jit-cache
            # walk and token math below — off must cost one dict lookup
            st.step()
            return
        toks = None
        first = _to_list(inputs)
        if first:
            shp = getattr(first[0], "shape", None)
            if shp is not None:
                try:
                    toks = int(np.prod([int(s) for s in shp])) or None
                except (TypeError, ValueError):
                    toks = None
        st.step(tokens=toks, trace_count=self._train_trace_count())

    # -- resilience (preemption, resume, fault hooks) ------------------
    @property
    def preempted(self):
        """True when the last ``fit`` exited early on a preemption
        after checkpointing its position — distinguish it from a
        completed run before e.g. exporting; continue with
        ``fit(resume=True)``."""
        return self._preempted

    def _maybe_poison(self, inputs, step_no):
        """Fault-injection hook (``resilience.faults`` site
        ``nan_step``): poison this step's first floating input with NaN
        so the full loss -> grads -> StepGuard path sees a genuine
        non-finite step. Shapes/dtypes are preserved — no recompile."""
        from ..resilience import faults
        if not faults.check("nan_step", str(step_no)):
            return inputs
        out, poisoned = [], False
        for b in _to_list(inputs):
            arr = np.asarray(b.numpy() if isinstance(b, Tensor) else b)
            if not poisoned and np.issubdtype(arr.dtype, np.floating):
                arr = np.full_like(arr, np.nan)
                poisoned = True
            out.append(arr)
        return out

    def _fire_synthetic_preempt(self, mgr, global_step):
        """Deliver a fault-harness preemption scheduled for this global
        step through the REAL signal path."""
        if mgr is None:
            return
        from ..resilience import faults
        if faults.check("preempt", str(global_step)):
            signal.raise_signal(signal.SIGTERM)

    def _maybe_preempt(self, mgr, epoch, steps_done, global_step,
                       epoch_steps=None, fire=True):
        """Step-boundary preemption point: deliver any synthetic
        preemption the fault harness scheduled, then honor a pending
        request by checkpointing the exact position ONCE and stopping
        the loops. A position at the end of an epoch is recorded as
        (epoch + 1, 0) so the resumed run doesn't replay the epoch
        boundary (on_epoch_end / evaluate / epoch saves). Returns True
        when preempted."""
        if mgr is None:
            return False
        if fire:
            self._fire_synthetic_preempt(mgr, global_step)
        if self._preempted:
            return True  # already checkpointed this preemption
        from ..resilience import preempt as _preempt
        if not _preempt.requested():
            return False
        if epoch_steps is not None and steps_done >= epoch_steps:
            epoch, steps_done = epoch + 1, 0
        self._resilient_save(mgr, epoch, steps_done, global_step)
        self.stop_training = True
        self._preempted = True
        # fit uses this to decide whether the epoch boundary was reached
        self._preempt_position = (epoch, steps_done, global_step)
        return True

    def _resilient_save(self, mgr, epoch, steps_done, global_step):
        """One versioned checkpoint (``resilience.CheckpointManager``):
        model + optimizer + RNG key; meta records the position
        ``fit(resume=True)`` restarts FROM (epoch, steps of that epoch
        already done, global step)."""
        from ..core import state as core_state
        objs = {"model": self.network.state_dict()}
        if self._optimizer is not None and hasattr(self._optimizer,
                                                   "state_dict"):
            objs["opt"] = self._optimizer.state_dict()
        rng = core_state.default_rng
        if rng._key_var is not None:
            objs["rng"] = np.asarray(rng._key_var._read())
        mgr.save(objs, global_step,
                 meta={"epoch": int(epoch),
                       "steps_done": int(steps_done),
                       "global_step": int(global_step)})

    def _restore_resilient(self, mgr):
        """Restore from the newest COMPLETE version (torn ones are
        skipped by the manager); None means no checkpoint yet — train
        from scratch. Returns (epoch, steps_done, global_step)."""
        from ..core import state as core_state
        from ..core.errors import CheckpointNotFoundError
        try:
            _step, objs, meta = mgr.load()
        except CheckpointNotFoundError:
            return None
        self.network.set_state_dict(objs["model"])
        if "opt" in objs and self._optimizer is not None and hasattr(
                self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(objs["opt"])
        if "rng" in objs:
            import jax.numpy as jnp
            rng = core_state.default_rng
            if rng._key_var is None:
                rng.seed(0)
            rng._key_var._write(jnp.asarray(objs["rng"]))
        return (int(meta.get("epoch", 0)), int(meta.get("steps_done", 0)),
                int(meta.get("global_step", 0)))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._loader(eval_data, batch_size, False, num_workers,
                              False)
        from .callbacks import CallbackList
        own = not isinstance(callbacks, CallbackList)
        cbks = (config_callbacks(callbacks, self, verbose=verbose,
                                 log_freq=log_freq,
                                 metrics=self._metrics_name())
                if own else callbacks)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            res = self.eval_batch(inputs, labels)
            if self._loss is not None and res:
                losses.append(res[0])
            logs = self._make_logs(res, prefix="eval_",
                                   has_loss=self._loss is not None)
            cbks.on_eval_batch_end(step, logs)
        final = {}
        if losses:
            final["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            final[f"eval_{self._mname(m)}"] = m.accumulate()
        cbks.on_eval_end(final)
        return final

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers,
                              False)
        outputs = []
        for batch in loader:
            inputs = batch[0] if isinstance(batch, (list, tuple)) else batch
            out = self.predict_batch([inputs])
            flat = out if isinstance(out, (list, tuple)) else [out]
            outputs.append([np.asarray(o._read()) for o in flat])
        if not outputs:
            return []
        n_out = len(outputs[0])
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    # -- helpers -------------------------------------------------------
    def _split_batch(self, batch):
        batch = _to_list(batch)
        n_label = len(_to_list(self._labels)) or 1
        return batch[:-n_label], batch[-n_label:]

    def _mname(self, m):
        n = m.name()
        return n if isinstance(n, str) else n[0]

    def _metrics_name(self):
        return ["loss"] + [self._mname(m) for m in self._metrics]

    def _make_logs(self, res, prefix="", has_loss=True):
        logs = {}
        metric_vals = res
        if has_loss and res:
            logs[prefix + "loss"] = res[0]
            metric_vals = res[1:]
        for m, v in zip(self._metrics, metric_vals):
            logs[prefix + self._mname(m)] = v
        return logs

    # -- persistence (reference :1472,:1560) ---------------------------
    def save(self, path, training=True):
        from .. import framework as fw
        from .. import jit
        if training:
            fw.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None and hasattr(self._optimizer,
                                                       "state_dict"):
                fw.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            spec = self._inputs
            jit.save(self.network, path, input_spec=spec)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework as fw
        sd = fw.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)
                and hasattr(self._optimizer, "set_state_dict")):
            self._optimizer.set_state_dict(fw.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .. import summary as _summary
        return _summary(self.network, input_size, dtype)

"""``paddle.static`` parity surface.

The reference's static-graph API (``python/paddle/base/framework.py:5768``
Program, ``executor.py:1162`` Executor) is a whole execution mode; on TPU
the jit capture cache *is* the static mode (SURVEY §7: "ProgramDesc/PIR +
StandaloneExecutor -> StableHLO/jaxpr as the IR; jit compile cache as the
executor"). This module provides the pieces user code actually touches:
``InputSpec`` (reference ``python/paddle/static/input_spec.py``) and thin
Program/Executor shims that delegate to the dynamic engine.
"""
from __future__ import annotations

import contextlib
import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


class InputSpec:
    """Shape/dtype/name signature of a program input (reference
    ``python/paddle/static/input_spec.py``). ``None`` dims are dynamic —
    ``jit.save`` exports them as symbolic dimensions."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(None if (d is None or (isinstance(d, int) and d < 0))
                           else int(d) for d in shape)
        self.dtype = str(np.dtype(convert_dtype(dtype)))
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        d = tensor._data if isinstance(tensor, Tensor) else tensor
        return cls(tuple(d.shape), str(d.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size=None):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("unbatch: 0-d spec")
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")

    def _example(self, dyn=2):
        """Concrete zeros for the discovery run (None dims -> ``dyn``)."""
        shape = tuple(dyn if d is None else d for d in self.shape)
        if "int" in self.dtype:
            return np.zeros(shape, self.dtype)
        return np.zeros(shape, self.dtype)


class Program:
    """API-parity shim of ``base/framework.py:5768``. On TPU the program
    IS the jit compile cache (SURVEY §7); a standalone mutable op-list
    program does not exist. Inference programs loaded via
    ``load_inference_model`` are runnable through ``Executor.run``."""

    def __init__(self, translated=None, feed_names=None, fetch_names=None):
        self._translated = translated
        self._feed_names = feed_names or []
        self._fetch_names = fetch_names or []

    def clone(self, for_test=False):
        return Program(self._translated, self._feed_names,
                       self._fetch_names)

    def global_block(self):
        raise NotImplementedError(
            "paddle_tpu has no mutable block IR: build models eagerly and "
            "compile with paddle.jit.to_static (the static-mode analog); "
            "export/serve with jit.save / static.save_inference_model")


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Reference ``base/framework.py program_guard``: swap the default
    programs for the with-block."""
    global _default_main, _default_startup
    old_main, old_startup = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = old_main, old_startup


class Executor:
    """Reference ``executor.py:1162`` surface. Runs inference programs
    loaded by ``load_inference_model``; ``run`` on the default (empty)
    program explains the dynamic-first migration path."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or _default_main
        if program._translated is None:
            raise NotImplementedError(
                "static graph construction is served by jit.to_static on "
                "this backend; Executor.run executes programs loaded via "
                "static.load_inference_model")
        feed = feed or {}
        args = [feed[n] for n in program._feed_names]
        outs = program._translated(*args)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if return_numpy:
            outs = [np.asarray(o._read()) for o in outs]
        return list(outs)

    def close(self):
        pass


def data(name, shape, dtype="float32", lod_level=0):
    """Reference ``static.data``: in the dynamic-first flow this is an
    ``InputSpec`` (exactly what jit.to_static/jit.save consume)."""
    return InputSpec(shape, dtype, name)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference ``static/io.py save_inference_model``. Dynamic-first
    form: ``feed_vars`` = list of InputSpec, ``fetch_vars`` = the Layer or
    @to_static function to export (the reference's static-Variable form
    has no analog without a block IR)."""
    from .. import jit
    layer = fetch_vars
    specs = list(feed_vars) if feed_vars else None
    jit.save(layer, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference ``static/io.py load_inference_model`` -> (program,
    feed_names, fetch_names); run via ``Executor.run``."""
    from .. import jit
    tl = jit.load(path_prefix)
    # exported avals = flattened [params..., inputs...]
    n_in = len(tl._exported.in_avals) - len(tl._names)
    feed_names = [f"x{i}" for i in range(n_in)]
    prog = Program(tl, feed_names, ["out"])
    return prog, feed_names, prog._fetch_names


def scope_guard(scope):
    import contextlib
    return contextlib.nullcontext()


def global_scope():
    return None


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


from . import nn  # noqa: E402,F401

__all__ = [
    "InputSpec", "Program", "Executor", "data", "default_main_program",
    "default_startup_program", "save_inference_model",
    "load_inference_model", "scope_guard", "global_scope",
    "CompiledProgram", "program_guard", "nn",
]

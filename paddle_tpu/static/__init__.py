"""``paddle.static`` parity surface.

The reference's static-graph API (``python/paddle/base/framework.py:5768``
Program, ``executor.py:1162`` Executor) is a whole execution mode; on TPU
the jit capture cache *is* the static mode (SURVEY §7: "ProgramDesc/PIR +
StandaloneExecutor -> StableHLO/jaxpr as the IR; jit compile cache as the
executor"). This module provides the pieces user code actually touches:
``InputSpec`` (reference ``python/paddle/static/input_spec.py``) and thin
Program/Executor shims that delegate to the dynamic engine.
"""
from __future__ import annotations

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


class InputSpec:
    """Shape/dtype/name signature of a program input (reference
    ``python/paddle/static/input_spec.py``). ``None`` dims are dynamic —
    ``jit.save`` exports them as symbolic dimensions."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(None if (d is None or (isinstance(d, int) and d < 0))
                           else int(d) for d in shape)
        self.dtype = str(np.dtype(convert_dtype(dtype)))
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        d = tensor._data if isinstance(tensor, Tensor) else tensor
        return cls(tuple(d.shape), str(d.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size=None):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("unbatch: 0-d spec")
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")

    def _example(self, dyn=2):
        """Concrete zeros for the discovery run (None dims -> ``dyn``)."""
        shape = tuple(dyn if d is None else d for d in self.shape)
        if "int" in self.dtype:
            return np.zeros(shape, self.dtype)
        return np.zeros(shape, self.dtype)


__all__ = ["InputSpec"]

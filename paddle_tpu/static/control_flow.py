"""Control-flow ops: ``cond`` / ``while_loop`` / ``switch_case`` / ``case``.

Capability analog of the reference's control-flow layer
(``python/paddle/static/nn/control_flow.py:1444`` cond, ``:687`` while_loop,
``:1065`` switch_case, ``:942`` case), TPU-native in mechanism: instead of
ConditionalBlock/While ops inside a ProgramDesc, these lower onto
``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` so a jit-captured train
step keeps data-dependent branching *inside* the single compiled XLA
program — the gap that previously forced a permanent eager fallback.

Semantics by execution mode (mirrors the reference's dygraph/static split):

- **Eager (dygraph)**: the predicate is concrete; exactly one branch runs,
  with full per-op autograd. Identical to the reference's dygraph behavior.
- **Under jit capture** (``paddle.jit.to_static`` discovery or replay): a
  real ``lax.cond``/``switch``/``while`` is emitted through the op funnel.
  Both/all branches are traced (the reference's static mode builds both
  blocks too); closed-over tensors (weights etc.) are discovered by a probe
  pass and hoisted into explicit operands so capture registers them as
  program inputs and gradients flow through ``jax.vjp`` of the whole op.

XLA constraints (documented divergences from the PIR executor):

- Branches must return the same structure with matching shapes/dtypes
  (static-shape compilation; the reference's runtime branch selection can
  tolerate shape mismatch, XLA cannot).
- Branch bodies must be functional under capture: in-place writes to
  tensors that exist outside the branch raise (a traced branch cannot
  mutate framework state; the same code still works eagerly). This includes
  the global RNG — use dropout outside branches or pass explicit seeds.
- ``while_loop`` under capture compiles to ``lax.while_loop`` when no
  operand needs gradients. When gradients ARE required (XLA has no
  reverse-mode while) it lowers to a **bounded ``lax.scan`` with
  early-exit masking**: the scan runs ``max_trip_count`` iterations
  (default from ``FLAGS_while_grad_max_trip_count``), each step applies
  the body only while the predicate still held (``jnp.where`` select on
  every carry leaf), so the loop stays inside the compiled program and
  differentiates through the selected iterations — the capability analog
  of the reference's differentiable While op
  (``python/paddle/static/nn/control_flow.py:687``). A loop still live
  at the bound warns at runtime (``jax.debug.callback``) and returns the
  truncated carry.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core import tensor as tensor_mod
from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "switch_case", "case"]


# --------------------------------------------------------------------------
# tracker shims
# --------------------------------------------------------------------------

class _BranchTracker:
    """Tracker installed while a branch body runs under capture.

    - substitutes hoisted operand values (``subs``: id(Tensor) -> value),
    - tracks branch-local tensors so their in-place writes stay local,
    - records ordered reads of outer tensors when probing,
    - forbids mutation of outer state (not representable in lax.cond).
    """

    def __init__(self, base, subs, record=False):
        self.base = base
        self.subs = subs
        self.record = record
        self.reads: list[Tensor] = []       # ordered, unique (probe mode)
        self._read_ids: set[int] = set()
        self.local: set[int] = set()
        self.local_env: dict[int, Any] = {}

    def on_create(self, t):
        self.local.add(id(t))
        if self.base is not None:
            self.base.on_create(t)

    def on_read(self, t):
        tid = id(t)
        if tid in self.subs:
            return self.subs[tid]
        if tid in self.local_env:
            return self.local_env[tid]
        if tid in self.local:
            return t._data
        if self.record and tid not in self._read_ids:
            self._read_ids.add(tid)
            self.reads.append(t)
        if self.base is not None:
            return self.base.on_read(t)
        return t._data

    def on_write(self, t, val):
        tid = id(t)
        if tid in self.local or tid in self.subs:
            self.local_env[tid] = val
            return
        raise RuntimeError(
            "control flow: in-place write to a tensor defined outside the "
            "branch/body is not supported under jit capture (a traced "
            "lax.cond/while branch cannot mutate framework state); return "
            "the new value from the branch instead")

    def on_grad_write(self, t):
        raise RuntimeError(
            "control flow: .backward() inside a branch/body is not "
            "supported; call it on the result of cond/while_loop")

    def add_host_sync(self, fn):
        if self.base is not None:
            self.base.add_host_sync(fn)


def _run_branch(fn: Callable, subs, record=False):
    """Run ``fn()`` under a _BranchTracker with grad recording off (the
    outer op's jax.vjp owns differentiation) and flatten the result *inside*
    the tracker context (branch-local in-place writes live in the tracker's
    local_env, not in Tensor._data). Returns (leaves, tree, tracker)."""
    tr = _BranchTracker(tensor_mod._tracker, subs, record=record)
    old = tensor_mod.set_tracker(tr)
    prev = state.set_grad_enabled(False)
    try:
        out = fn()
        leaves, tree = _flatten_out(out)
    finally:
        state.set_grad_enabled(prev)
        tensor_mod.set_tracker(old)
    return leaves, tree, tr


def _hoist(fns):
    """Probe every branch once, collecting the ordered union of
    outer-tensor reads (weights and other closures) to hoist as explicit
    operands. Returns (trees, reads, leaves-per-fn)."""
    reads: list[Tensor] = []
    read_ids: set[int] = set()
    trees = []
    leaves_all = []
    for fn in fns:
        leaves, tree, tr = _run_branch(fn, {}, record=True)
        trees.append(tree)
        leaves_all.append(leaves)
        for t in tr.reads:
            if id(t) not in read_ids:
                read_ids.add(id(t))
                reads.append(t)
    return trees, reads, leaves_all


# --------------------------------------------------------------------------
# undefined-slot unification (dy2static support)
#
# dy2static's escape elimination (early return / break / continue -> flag
# form) can leave a state slot holding the UNDEF sentinel on one branch
# while the other branch binds it to a tensor (the reference fills such
# slots with RETURN_NO_VALUE / UndefinedVar dummies,
# ``python/paddle/jit/dy2static/return_transformer.py``). When the caller
# passes ``_undef_fill``, slots that are UNDEF on one side and a tensor on
# the other are filled with typed zeros — semantically dead values, guarded
# by the flag that accompanies them.
# --------------------------------------------------------------------------

def _tree_has(tree, sentinel):
    kind = tree[0]
    if kind == "c":
        return tree[1] is sentinel
    if kind in ("list", "tuple"):
        return any(_tree_has(t, sentinel) for t in tree[1])
    if kind == "dict":
        return any(_tree_has(t, sentinel) for t in tree[1].values())
    return False


def _needs_unify(a, b, sentinel):
    """True when the trees disagree at a position the fill can repair:
    sentinel-vs-anything or plain-scalar-constant-vs-tensor."""
    ka, kb = a[0], b[0]
    if ka == "c" and (a[1] is sentinel
                      or (kb == "T" and isinstance(a[1],
                                                   (bool, int, float)))):
        return True
    if kb == "c" and (b[1] is sentinel
                      or (ka == "T" and isinstance(b[1],
                                                   (bool, int, float)))):
        return True
    if ka == kb == "c" and isinstance(a[1], (bool, int, float)) \
            and isinstance(b[1], (bool, int, float)) and a[1] != b[1]:
        return True
    if ka == kb and ka in ("list", "tuple") and len(a[1]) == len(b[1]):
        return any(_needs_unify(x, y, sentinel)
                   for x, y in zip(a[1], b[1]))
    if ka == kb == "dict":
        return any(_needs_unify(a[1][k], b[1][k], sentinel)
                   for k in a[1] if k in b[1])
    return False


def _sub_fill(obj, other_tree, other_leaves, sentinel):
    """Replace ``sentinel`` leaves of ``obj`` with typed zeros (or the
    matching constant) taken from the corresponding position of the
    other branch's probe; promote plain scalar constants paired with a
    tensor on the other side (a converted flag set like ``brk = True``
    is a python constant in one branch and a carried tensor in the
    other)."""
    if obj is sentinel:
        if other_tree[0] == "T":
            ref = other_leaves[other_tree[1]]
            return Tensor(jnp.zeros(jnp.shape(ref),
                                    getattr(ref, "dtype", None)
                                    or jnp.result_type(ref)))
        if other_tree[0] == "c" and isinstance(other_tree[1],
                                               (bool, int, float)):
            return other_tree[1]
        return obj
    if isinstance(obj, (bool, int, float)) and other_tree[0] == "T":
        ref = other_leaves[other_tree[1]]
        return Tensor(jnp.asarray(obj, getattr(ref, "dtype", None)
                                  or jnp.result_type(ref)))
    if isinstance(obj, (bool, int, float)) and other_tree[0] == "c" \
            and isinstance(other_tree[1], (bool, int, float)) \
            and obj != other_tree[1]:
        # branches bind the SAME name to DIFFERENT constants (cont=True
        # in one arm, the False reset in the other): only a traced
        # select can represent the merge
        return Tensor(jnp.asarray(obj, jnp.result_type(obj,
                                                       other_tree[1])))
    if isinstance(obj, (list, tuple)) and other_tree[0] in ("list", "tuple") \
            and len(other_tree[1]) == len(obj):
        return type(obj)(_sub_fill(o, t, other_leaves, sentinel)
                         for o, t in zip(obj, other_tree[1]))
    if isinstance(obj, dict) and other_tree[0] == "dict":
        return {k: (_sub_fill(v, other_tree[1][k], other_leaves, sentinel)
                    if k in other_tree[1] else v)
                for k, v in obj.items()}
    return obj


def _filled_fn(fn, other_tree, other_leaves, sentinel):
    def wrapped():
        return _sub_fill(fn(), other_tree, other_leaves, sentinel)
    return wrapped


# --------------------------------------------------------------------------
# output-structure handling
# --------------------------------------------------------------------------

def _flatten_out(out):
    """nest of Tensors/values -> (flat jax values, treedef with holes).

    Must run while the tracker that produced ``out`` is active: values are
    taken through ``_read`` so substitutions and branch-local writes
    resolve."""
    leaves = []

    def go(o):
        if isinstance(o, Tensor):
            leaves.append(o._read())
            return ("T", len(leaves) - 1)
        if isinstance(o, (list, tuple)):
            return (type(o).__name__, [go(x) for x in o])
        if isinstance(o, dict):
            return ("dict", {k: go(o[k]) for k in sorted(o)})
        return ("c", o)

    tree = go(out)
    return leaves, tree


def _rebuild_out(tree, tensors):
    kind = tree[0]
    if kind == "T":
        return tensors[tree[1]]
    if kind == "list":
        return [_rebuild_out(t, tensors) for t in tree[1]]
    if kind == "tuple":
        return tuple(_rebuild_out(t, tensors) for t in tree[1])
    if kind == "dict":
        return {k: _rebuild_out(v, tensors) for k, v in tree[1].items()}
    return tree[1]


def _struct_sig(tree):
    kind = tree[0]
    if kind == "T":
        return "T"
    if kind in ("list", "tuple"):
        return (kind, tuple(_struct_sig(t) for t in tree[1]))
    if kind == "dict":
        return ("dict", tuple((k, _struct_sig(v))
                              for k, v in sorted(tree[1].items())))
    v = tree[1]
    if isinstance(v, (np.ndarray, jax.Array)):  # value-compare raw arrays
        a = np.asarray(v)
        return ("arr", a.shape, str(a.dtype), a.tobytes())
    try:
        hash(v)
        return ("c", v)
    except TypeError:
        return ("c", type(v).__name__, repr(v)[:200])


def _check_same_structure(trees, what):
    sigs = [_struct_sig(t) for t in trees]
    if any(s != sigs[0] for s in sigs[1:]):
        raise ValueError(
            f"{what}: branches must return the same structure of tensors "
            f"(got {sigs})")


def _as_bool_scalar(v):
    return jnp.reshape(jnp.asarray(v), ()).astype(bool)


def _needs_grad(tensors):
    return state.is_grad_enabled() and any(
        isinstance(t, Tensor) and not t.stop_gradient for t in tensors)


# --------------------------------------------------------------------------
# cond
# --------------------------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None,
         _undef_fill=None):
    """``true_fn()`` if ``pred`` else ``false_fn()`` (reference
    ``static/nn/control_flow.py:1444``). Works eagerly (runs one branch)
    and under jit capture (emits ``lax.cond``)."""
    true_fn = true_fn if true_fn is not None else (lambda: None)
    false_fn = false_fn if false_fn is not None else (lambda: None)
    if not callable(true_fn) or not callable(false_fn):
        raise TypeError("cond: true_fn and false_fn must be callable")

    if tensor_mod._tracker is None:
        return true_fn() if bool(unwrap(pred)) else false_fn()

    trees, reads, leaves = _hoist([true_fn, false_fn])
    tree_t, tree_f = trees
    if _undef_fill is not None and _needs_unify(tree_t, tree_f,
                                                _undef_fill):
        true_fn = _filled_fn(true_fn, tree_f, leaves[1], _undef_fill)
        false_fn = _filled_fn(false_fn, tree_t, leaves[0], _undef_fill)
        trees, reads, leaves = _hoist([true_fn, false_fn])
        tree_t, tree_f = trees
    _check_same_structure([tree_t, tree_f], "cond")

    pred_t = pred if isinstance(pred, Tensor) else Tensor(jnp.asarray(pred))
    read_ids = [id(t) for t in reads]

    def _cond_impl(pred_v, *op_vals):
        def mk(fn):
            def branch(vals):
                leaves, _, _ = _run_branch(fn, dict(zip(read_ids, vals)))
                return tuple(leaves)
            return branch
        return jax.lax.cond(_as_bool_scalar(pred_v), mk(true_fn),
                            mk(false_fn), tuple(op_vals))

    flat = apply("cond", _cond_impl, pred_t, *reads)
    return _rebuild_out(tree_t, list(flat))


# --------------------------------------------------------------------------
# switch_case / case
# --------------------------------------------------------------------------

def _normalize_branch_fns(branch_fns, default):
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif isinstance(branch_fns, (list, tuple)):
        if branch_fns and not isinstance(branch_fns[0], (list, tuple)):
            pairs = list(enumerate(branch_fns))
        else:
            pairs = sorted((int(k), fn) for k, fn in branch_fns)
    else:
        raise TypeError("switch_case: branch_fns must be dict|list|tuple")
    keys = [k for k, _ in pairs]
    if len(set(keys)) != len(keys):
        raise ValueError(f"switch_case: duplicate branch keys {keys}")
    for _, fn in pairs:
        if not callable(fn):
            raise TypeError("switch_case: branch fns must be callable")
    if default is None:
        default = pairs[-1][1]  # reference: max index wins when no match
    elif not callable(default):
        raise TypeError("switch_case: default must be callable")
    return pairs, default


def switch_case(branch_index, branch_fns, default=None, name=None):
    """C-style switch (reference ``static/nn/control_flow.py:1065``):
    run ``branch_fns[branch_index]``, else ``default``."""
    pairs, default = _normalize_branch_fns(branch_fns, default)

    if tensor_mod._tracker is None:
        idx = int(unwrap(branch_index))
        for k, fn in pairs:
            if k == idx:
                return fn()
        return default()

    fns = [fn for _, fn in pairs] + [default]
    keys = [k for k, _ in pairs]
    trees, reads, _ = _hoist(fns)
    _check_same_structure(trees, "switch_case")

    idx_t = (branch_index if isinstance(branch_index, Tensor)
             else Tensor(jnp.asarray(branch_index)))
    read_ids = [id(t) for t in reads]

    def _switch_impl(idx_v, *op_vals):
        iv = jnp.reshape(jnp.asarray(idx_v), ()).astype(jnp.int32)
        sel = jnp.full((), len(keys), jnp.int32)  # default slot
        for i, k in enumerate(keys):
            sel = jnp.where(iv == k, jnp.int32(i), sel)

        def mk(fn):
            def branch(vals):
                leaves, _, _ = _run_branch(fn, dict(zip(read_ids, vals)))
                return tuple(leaves)
            return branch

        return jax.lax.switch(sel, [mk(f) for f in fns], tuple(op_vals))

    flat = apply("switch_case", _switch_impl, idx_t, *reads)
    return _rebuild_out(trees[0], list(flat))


def case(pred_fn_pairs, default=None, name=None):
    """if/elif/else chain (reference ``static/nn/control_flow.py:942``):
    first true pred wins; ``default`` (or the last fn) when none is."""
    if not isinstance(pred_fn_pairs, (list, tuple)) or not pred_fn_pairs:
        raise TypeError("case: pred_fn_pairs must be a non-empty list|tuple")
    for p in pred_fn_pairs:
        if not (isinstance(p, (list, tuple)) and len(p) == 2
                and callable(p[1])):
            raise TypeError("case: elements must be (pred, callable) pairs")
    preds = [p for p, _ in pred_fn_pairs]
    fns = [fn for _, fn in pred_fn_pairs]
    if default is None:
        default = fns[-1]

    if tensor_mod._tracker is None:
        for p, fn in zip(preds, fns):
            if bool(unwrap(p)):
                return fn()
        return default()

    all_fns = list(fns) + [default]
    trees, reads, _ = _hoist(all_fns)
    _check_same_structure(trees, "case")

    pred_ts = [p if isinstance(p, Tensor) else Tensor(jnp.asarray(p))
               for p in preds]
    read_ids = [id(t) for t in reads]
    n = len(fns)

    def _case_impl(*vals):
        pred_vs, op_vals = vals[:n], vals[n:]
        stacked = jnp.stack([_as_bool_scalar(p) for p in pred_vs]
                            + [jnp.asarray(True)])
        sel = jnp.argmax(stacked).astype(jnp.int32)  # first True wins

        def mk(fn):
            def branch(ops):
                leaves, _, _ = _run_branch(fn, dict(zip(read_ids, ops)))
                return tuple(leaves)
            return branch

        return jax.lax.switch(sel, [mk(f) for f in all_fns], tuple(op_vals))

    flat = apply("case", _case_impl, *pred_ts, *reads)
    return _rebuild_out(trees[0], list(flat))


# --------------------------------------------------------------------------
# while_loop
# --------------------------------------------------------------------------

def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_trip_count=None, _undef_fill=None):
    """Repeat ``body`` while ``cond`` holds (reference
    ``static/nn/control_flow.py:687``).

    ``max_trip_count`` (extension): trip bound used only for the
    differentiable lowering under jit capture; defaults to
    ``FLAGS_while_grad_max_trip_count``."""
    if not callable(cond) or not callable(body):
        raise TypeError("while_loop: cond and body must be callable")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("while_loop: loop_vars must be a non-empty "
                        "list|tuple")
    # Python-scalar loop vars become Tensors so the carry stays a traced
    # leaf (a plain `0` counter would otherwise be a changing constant and
    # trip the structure check under capture).
    loop_vars = type(loop_vars)(_tensorize(v) for v in loop_vars)

    def run_python_loop():
        vars_ = tuple(loop_vars)
        while bool(unwrap(cond(*vars_))):
            out = body(*vars_)
            if not isinstance(out, (list, tuple)):
                out = (out,)
            if len(out) != len(vars_):
                raise ValueError(
                    "while_loop: body must return as many values as "
                    f"loop_vars (got {len(out)}, want {len(vars_)})")
            vars_ = tuple(out)
        return list(vars_) if isinstance(loop_vars, list) else vars_

    if tensor_mod._tracker is None:
        return run_python_loop()

    # ---- capture: probe for closed-over invariants and the carry tree
    carry_leaves, carry_tree = _flatten_out(tuple(loop_vars))
    carry_ts = list(_iter_tensors(loop_vars))
    carry_ids = [id(t) for t in carry_ts]

    def probe_body():
        out = body(*loop_vars)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    (_, body_tree), reads, bleaves = _hoist([lambda: cond(*loop_vars),
                                             probe_body])
    if _undef_fill is not None and body_tree[0] in ("tuple", "list") \
            and len(body_tree[1]) == len(loop_vars) \
            and _needs_unify(carry_tree, body_tree, _undef_fill):
        # two repairable disagreements between carry and body:
        # - a slot UNDEF at entry that becomes a tensor inside the body
        #   (__pt_retv before the first early return): seed the carry
        #   with typed zeros from the body probe;
        # - a slot that is a tensor in the carry but a python constant
        #   in the body output (a flag reset like ``cont = False``):
        #   promote the body's constant to the carry's tensor type.
        loop_vars = type(loop_vars)(
            _sub_fill(v, t, bleaves[1], _undef_fill)
            for v, t in zip(loop_vars, body_tree[1]))
        carry_leaves, carry_tree = _flatten_out(tuple(loop_vars))
        carry_ts = list(_iter_tensors(loop_vars))
        carry_ids = [id(t) for t in carry_ts]
        orig_body, final_tree, final_leaves = body, carry_tree, carry_leaves

        def body(*vs):
            out = orig_body(*vs)
            out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
            return tuple(_sub_fill(o, t, final_leaves, _undef_fill)
                         for o, t in zip(out, final_tree[1]))

        (_, body_tree), reads, bleaves = _hoist([lambda: cond(*loop_vars),
                                                 probe_body])
    _check_same_structure([carry_tree, body_tree], "while_loop")
    reads = [t for t in reads if id(t) not in set(carry_ids)]
    read_ids = [id(t) for t in reads]
    n_carry = len(carry_leaves)

    needs_grad = _needs_grad(carry_ts + reads)
    if needs_grad:
        bound = int(max_trip_count
                    if max_trip_count is not None
                    else state.get_flag("while_grad_max_trip_count"))
        if bound <= 0:
            # explicit opt-out of the scan lowering: Python unroll during
            # discovery -> to_static eager fallback on replay, where the
            # loop differentiates through the tape
            return run_python_loop()

    def _make_cond_body(vals):
        inv = dict(zip(read_ids, vals[n_carry:]))

        def wrap_vars(carry):
            ts = [Tensor(v) for v in carry]
            return _rebuild_out(carry_tree, ts)

        def subs_for(carry):
            # closures over the ORIGINAL loop-var objects see the current
            # carry (the static-mode semantics: the var IS the loop slot)
            s = dict(inv)
            s.update(zip(carry_ids, carry))
            return s

        def cond_w(carry):
            leaves, _, _ = _run_branch(
                lambda: cond(*_as_tuple(wrap_vars(carry))),
                subs_for(carry))
            return _as_bool_scalar(leaves[0])

        def body_w(carry):
            def run():
                out = body(*_as_tuple(wrap_vars(carry)))
                return tuple(out) if isinstance(out, (list, tuple)) \
                    else (out,)
            leaves, _, _ = _run_branch(run, subs_for(carry))
            return tuple(leaves)

        return cond_w, body_w

    def _while_impl(*vals):
        cond_w, body_w = _make_cond_body(vals)
        return jax.lax.while_loop(cond_w, body_w, tuple(vals[:n_carry]))

    def _while_scan_impl(*vals):
        # differentiable lowering: bounded scan, body masked off once the
        # predicate first fails (reverse-mode flows through the selected
        # iterations only; jnp.where's vjp routes zero cotangent to the
        # unselected branch)
        cond_w, body_w = _make_cond_body(vals)
        init = tuple(vals[:n_carry])

        def step(carry, _):
            done, vars_ = carry
            live = jnp.logical_and(jnp.logical_not(done), cond_w(vars_))
            new_vars = body_w(vars_)
            sel = tuple(jnp.where(live, n, o)
                        for n, o in zip(new_vars, vars_))
            return (jnp.logical_or(done, jnp.logical_not(live)), sel), None

        (done, final), _ = jax.lax.scan(
            step, (jnp.zeros((), bool), init), None, length=bound)
        still_live = jnp.logical_and(jnp.logical_not(done), cond_w(final))

        def _warn(live):
            if bool(live):
                # PDT206 through the graph-lint registry: honors the
                # PDTPU_ANALYSIS mode flag and analysis.suppress()
                from ..analysis import report_runtime
                report_runtime(
                    "PDT206",
                    "while_loop: differentiable scan lowering hit its "
                    f"trip bound ({bound}) with the predicate still "
                    "true; result is truncated. Raise max_trip_count or "
                    "FLAGS_while_grad_max_trip_count.",
                    file="<while_loop>")
        jax.debug.callback(_warn, still_live)
        return final

    flat = apply("while_loop",
                 _while_scan_impl if needs_grad else _while_impl,
                 *carry_ts, *reads)
    res = _rebuild_out(carry_tree, list(flat))
    return list(res) if isinstance(loop_vars, list) else res


def _as_tuple(x):
    return x if isinstance(x, tuple) else tuple(x)


def _tensorize(v):
    """Promote scalar/array loop vars to Tensors; leave nests to the user
    (the reference requires loop_vars to be Variables too)."""
    if isinstance(v, Tensor) or isinstance(v, (list, tuple, dict)):
        return v
    if isinstance(v, (bool, int, float, np.ndarray, np.generic, jax.Array)):
        return Tensor(jnp.asarray(v))
    return v


def _iter_tensors(obj):
    """Tensor leaves in _flatten_out's traversal order (same walk as
    jit._flatten_tensors; kept in lock-step with _flatten_out because
    carry ids are zipped positionally against carry leaves)."""
    from ..jit import _flatten_tensors
    return iter(_flatten_tensors(obj, []))

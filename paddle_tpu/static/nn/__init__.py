"""``paddle.static.nn`` parity surface (reference
``python/paddle/static/nn/__init__.py``): the pieces with TPU-side meaning.
Control flow lowers onto lax primitives; the layer builders of the
reference's static mode (fc, embedding, ...) are the dygraph layers here —
static mode IS the jit capture cache (see ``paddle_tpu.static``)."""
from ..control_flow import case, cond, switch_case, while_loop  # noqa: F401

__all__ = ["cond", "while_loop", "switch_case", "case"]

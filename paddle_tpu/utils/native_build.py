"""Shared compile-on-first-use loader for the native C++ runtime pieces
(io loader, store server): mtime-based rebuild, double-checked caching,
graceful None on a missing toolchain so callers can fall back to Python.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_cache: dict = {}


def build_and_load(src: str, so: str, flags=("-O2",)):
    """Compile ``src`` -> ``so`` (if stale) and dlopen it; None when the
    toolchain is unavailable or the build fails. Results (including
    failure) are cached per ``so`` path."""
    if so in _cache:
        lib = _cache[so]
        return lib or None
    with _lock:
        if so in _cache:
            lib = _cache[so]
            return lib or None
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                # build to a per-pid temp + atomic rename: concurrent
                # processes (test subprocesses) must not read a half-
                # written .so
                tmp = f"{so}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", *flags, "-shared", "-fPIC", "-std=c++17",
                     "-pthread", src, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except Exception:
            lib = False
        _cache[so] = lib
        return lib or None

"""``paddle.utils.cpp_extension`` parity — custom C++ ops (SURVEY C31).

Reference ``python/paddle/utils/cpp_extension/`` (``load`` :dynamic JIT
build, CppExtension/setup) building ops against the C++ framework. TPU
split: device-side custom kernels are Pallas (`core.dispatch.primitive`
over a ``pallas_call`` — the custom-kernel path proper); HOST-side custom
C++ ops compile with g++ at load() time and execute through
``jax.pure_callback``, so they compose with jit/vmap tracing while the
C++ runs on the host (the analog of the reference's CPU custom kernels).

Declared signature convention (kept deliberately C-simple): each op is
``void f(const float* in, float* out, int64_t n)`` elementwise-style, or
any ctypes signature the caller wires explicitly via ``bind``.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np


class _OpModule:
    """Result of ``load``: exposes each bound op as a framework op."""

    def __init__(self, lib, name):
        self._lib = lib
        self.__name__ = name

    def bind(self, symbol, op_impl, out_spec=None):
        """Register ``symbol`` with an explicit wrapper ``op_impl(lib,
        *arrays) -> array`` as a differentiable-opaque framework op.

        ``out_spec(*avals) -> ShapeDtypeStruct`` declares the output
        contract (the InferMeta analog); default = same shape/dtype as
        the first input (the elementwise convention).
        """
        import jax

        from ..core.dispatch import apply

        lib = self._lib

        def op(*tensors, **kwargs):
            def impl(*vals):
                if out_spec is not None:
                    out_shape = out_spec(*vals)
                else:
                    ex = vals[0]
                    out_shape = jax.ShapeDtypeStruct(ex.shape, ex.dtype)
                return jax.pure_callback(
                    lambda *a: op_impl(lib, *[np.asarray(x) for x in a]),
                    out_shape, *vals, vmap_method="sequential")

            return apply(symbol, impl, *tensors, **kwargs)

        setattr(self, symbol, op)
        return op

    def bind_elementwise(self, symbol):
        """Convenience for the ``void f(const float*, float*, int64_t)``
        convention."""
        fn = getattr(self._lib, symbol)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]

        def run(lib, x):
            x = np.ascontiguousarray(x, np.float32)
            out = np.empty_like(x)
            fn(x.ctypes.data, out.ctypes.data, x.size)
            return out

        return self.bind(symbol, run)


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """Reference ``cpp_extension.load``: compile ``sources`` (C++ files)
    into a shared library and return a module handle whose ops are bound
    via ``bind``/``bind_elementwise``."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [sources] if isinstance(sources, str) else list(sources)
    needs_build = (not os.path.exists(so_path) or any(
        os.path.getmtime(s) > os.path.getmtime(so_path) for s in srcs))
    if needs_build:
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
               + (extra_cxx_cflags or []) + srcs + ["-o", so_path])
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return _OpModule(ctypes.CDLL(so_path), name)


class CppExtension:
    """setup()-style descriptor (reference parity; ``load`` is the
    JIT path actually exercised on this backend)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources


def setup(name=None, ext_modules=None, **kwargs):
    """Reference ``cpp_extension.setup`` minimal: builds each extension
    eagerly via ``load`` (no pip install machinery in this image)."""
    mods = []
    exts = ext_modules if isinstance(ext_modules, list) else [ext_modules]
    for ext in exts:
        mods.append(load(name or "custom_op", ext.sources))
    return mods


__all__ = ["load", "setup", "CppExtension"]

"""``paddle.utils.dlpack`` — zero-copy tensor interchange (reference
``python/paddle/utils/dlpack.py``). jax arrays speak dlpack natively."""
from __future__ import annotations

from ..core.tensor import Tensor


def to_dlpack(x: Tensor):
    import jax
    import numpy as np
    v = x._read() if isinstance(x, Tensor) else x
    try:
        if hasattr(v, "__dlpack__"):
            return v.__dlpack__()
        return jax.dlpack.to_dlpack(v)
    except Exception:
        # remote/tunnel device buffers can't be externally referenced:
        # export a host copy's capsule (zero-copy only host-side)
        return np.asarray(v).__dlpack__()


def from_dlpack(capsule) -> Tensor:
    import jax.numpy as jnp
    import numpy as np
    if hasattr(capsule, "__dlpack__"):  # modern protocol object
        return Tensor(jnp.asarray(np.from_dlpack(capsule)))
    return Tensor(jnp.asarray(np.from_dlpack(_CapsuleHolder(capsule))))


class _CapsuleHolder:
    """Adapts a raw PyCapsule to the __dlpack__ protocol numpy expects."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


__all__ = ["to_dlpack", "from_dlpack"]

"""``paddle.utils`` parity: dlpack interchange, deprecated decorator,
try_import, unique_name (reference ``python/paddle/utils/``)."""
from __future__ import annotations

import functools
import importlib
import itertools
import warnings

from ..core.tensor import Tensor
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """Reference ``utils/deprecated.py`` decorator."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__qualname__}' is deprecated since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f" ({reason})"
            if level < 2:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            else:
                raise RuntimeError(msg)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def try_import(module_name, err_msg=None):
    """Reference ``utils/lazy_import.py``."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"required optional package {module_name!r} is not "
            f"installed") from None


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def __call__(self, key):
        c = self._counters.setdefault(key, itertools.count())
        return f"{key}_{next(c)}"


generate = _UniqueNameGenerator()


class unique_name:
    """Reference ``base/unique_name.py`` surface."""
    generate = staticmethod(generate)

    @staticmethod
    def guard(prefix=None):
        import contextlib
        return contextlib.nullcontext()


def run_check():
    """Reference ``utils/install_check.py run_check``: a tiny train step
    on the current backend."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    loss = (net(x) ** 2).mean()
    loss.backward()
    opt.step()
    import jax
    print(f"paddle_tpu is installed successfully! "
          f"(backend: {jax.default_backend()}, "
          f"devices: {len(jax.devices())})")


__all__ = ["deprecated", "try_import", "unique_name", "generate",
           "run_check", "dlpack"]

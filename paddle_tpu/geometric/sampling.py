"""Neighbor sampling over CSC graphs (reference
``python/paddle/geometric/sampling/neighbors.py``:23,172).

Host-side numpy: sampling is input-pipeline work with data-dependent output
shapes. Uses the framework RNG seed (``paddle.seed``) for reproducibility.
"""
from __future__ import annotations

import numpy as np

from ..core import state
from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def _rng():
    # derive a host seed from the framework RNG stream (paddle.seed analog)
    import jax
    key = np.asarray(jax.random.key_data(state.default_rng.next_key()))
    return np.random.default_rng(key.astype(np.uint32))


def _sample(row, colptr, input_nodes, sample_size, eids, weights=None):
    row = np.asarray(unwrap(row)).reshape(-1)
    colptr = np.asarray(unwrap(colptr)).reshape(-1)
    nodes = np.asarray(unwrap(input_nodes)).reshape(-1)
    eids_np = None if eids is None else np.asarray(unwrap(eids)).reshape(-1)
    w = None if weights is None else np.asarray(unwrap(weights)).reshape(-1)
    rng = _rng()

    out_neigh, out_eids, out_count = [], [], np.empty(len(nodes), np.int32)
    for i, n in enumerate(nodes):
        beg, end = int(colptr[n]), int(colptr[int(n) + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(beg, end)
        elif w is not None:
            # zero-weight edges are unsamplable; a node may yield fewer
            # than sample_size neighbors
            p = w[beg:end].astype(np.float64)
            nz = np.flatnonzero(p > 0)
            k = min(sample_size, len(nz))
            if k == 0:
                pick = np.empty(0, np.int64)
            else:
                pick = beg + rng.choice(
                    nz, size=k, replace=False, p=p[nz] / p[nz].sum())
        else:
            pick = beg + rng.choice(deg, size=sample_size, replace=False)
        out_count[i] = len(pick)
        out_neigh.append(row[pick])
        if eids_np is not None:
            out_eids.append(eids_np[pick])

    neigh = (np.concatenate(out_neigh) if out_neigh
             else np.empty(0, row.dtype))
    res = [Tensor(neigh), Tensor(out_count)]
    if eids_np is not None:
        res.append(Tensor(np.concatenate(out_eids) if out_eids
                          else np.empty(0, row.dtype)))
    return res


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    res = _sample(row, colptr, input_nodes, sample_size,
                  eids if return_eids else None)
    return tuple(res) if return_eids else (res[0], res[1])


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    res = _sample(row, colptr, input_nodes, sample_size,
                  eids if return_eids else None, weights=edge_weight)
    return tuple(res) if return_eids else (res[0], res[1])

"""Graph-learning ops (reference ``python/paddle/geometric/``).

The message-passing/segment math runs as jax segment ops on device (MXU/VPU
friendly scatter-adds XLA lowers natively); the graph-prep ops
(reindex/sampling) are host-side input-pipeline work, as on the reference
where they run on CPU ints — keeping data-dependent shapes out of compiled
programs.
"""
from .math import segment_max, segment_mean, segment_min, segment_sum
from .message_passing import send_u_recv, send_ue_recv, send_uv
from .reindex import reindex_graph, reindex_heter_graph
from .sampling import sample_neighbors, weighted_sample_neighbors

__all__ = [
    'send_u_recv',
    'send_ue_recv',
    'send_uv',
    'segment_sum',
    'segment_mean',
    'segment_min',
    'segment_max',
    'reindex_graph',
    'reindex_heter_graph',
    'sample_neighbors',
    'weighted_sample_neighbors',
]

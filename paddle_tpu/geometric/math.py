"""Segment reductions (reference ``python/paddle/geometric/math.py``:23-254).

``segment_ids`` must be sorted non-decreasing (reference contract); empty
segments produce 0 rows. The segment count is read from the concrete ids on
the host (these are graph-prep ops; under ``jit.to_static`` capture pass a
pre-computed dense graph instead).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap


def _num_segments(segment_ids) -> int:
    ids = np.asarray(unwrap(segment_ids))
    return int(ids.max()) + 1 if ids.size else 0


def seg_reduce(msg, ids, num, op, indices_are_sorted=False):
    """Shared segment sum/mean/min/max with the reference's empty-segment
    contract: rows receiving no message are 0 (jax's min/max identities —
    ±inf for floats, iinfo extremes for ints — are replaced)."""
    ids = ids.astype(jnp.int32)
    kw = dict(num_segments=num, indices_are_sorted=indices_are_sorted)
    if op == "sum":
        return jax.ops.segment_sum(msg, ids, **kw)
    if op == "mean":
        total = jax.ops.segment_sum(msg, ids, **kw)
        cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype),
                                  ids, **kw)
        return total / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (msg.ndim - 1))
    if op == "max":
        out = jax.ops.segment_max(msg, ids, **kw)
    elif op == "min":
        out = jax.ops.segment_min(msg, ids, **kw)
    else:
        raise ValueError(f"unsupported reduce op {op!r}")
    cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), jnp.int32), ids, **kw)
    empty = (cnt == 0).reshape((-1,) + (1,) * (msg.ndim - 1))
    return jnp.where(empty, jnp.zeros_like(out), out)


@primitive
def _segment_reduce(data, segment_ids, num_segments=0, op="sum"):
    return seg_reduce(data, segment_ids, num_segments, op,
                      indices_are_sorted=True)


def segment_sum(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_num_segments(segment_ids), op="sum")


def segment_mean(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_num_segments(segment_ids), op="mean")


def segment_min(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_num_segments(segment_ids), op="min")


def segment_max(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids,
                           num_segments=_num_segments(segment_ids), op="max")

"""Graph reindexing (reference ``python/paddle/geometric/reindex.py``:25,139).

Host-side int bookkeeping (graph prep runs in the input pipeline on TPU —
data-dependent output shapes must stay out of compiled programs). The
hashtable value/index buffers of the GPU fast path are accepted and ignored.
"""
from __future__ import annotations

import numpy as np

from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def _reindex(x, neighbor_lists):
    x = np.asarray(unwrap(x)).reshape(-1)
    mapping = {int(n): i for i, n in enumerate(x)}
    out_nodes = list(x)
    srcs = []
    for neigh in neighbor_lists:
        src = np.empty(len(neigh), dtype=np.int64)
        for j, n in enumerate(np.asarray(neigh).reshape(-1)):
            n = int(n)
            idx = mapping.get(n)
            if idx is None:
                idx = mapping[n] = len(out_nodes)
                out_nodes.append(n)
            src[j] = idx
        srcs.append(src)
    return srcs, np.asarray(out_nodes, dtype=x.dtype)


def _dst(count, n_inputs):
    cnt = np.asarray(unwrap(count)).reshape(-1).astype(np.int64)
    return np.repeat(np.arange(n_inputs, dtype=np.int64), cnt)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Renumber ``x`` + ``neighbors`` to a dense [0, n) id space; returns
    (reindex_src, reindex_dst, out_nodes) with ``x`` ids first."""
    n_inputs = len(np.asarray(unwrap(x)).reshape(-1))
    srcs, out_nodes = _reindex(x, [np.asarray(unwrap(neighbors))])
    return (Tensor(srcs[0]), Tensor(_dst(count, n_inputs)), Tensor(out_nodes))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: ``neighbors``/``count`` are per-edge-type
    lists sharing one node id space; outputs are concatenated per type."""
    n_inputs = len(np.asarray(unwrap(x)).reshape(-1))
    srcs, out_nodes = _reindex(
        x, [np.asarray(unwrap(n)) for n in neighbors])
    dsts = [_dst(c, n_inputs) for c in count]
    return (Tensor(np.concatenate(srcs)), Tensor(np.concatenate(dsts)),
            Tensor(out_nodes))

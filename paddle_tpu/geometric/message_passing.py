"""Gather-scatter message passing (reference
``python/paddle/geometric/message_passing/send_recv.py``:36,187,392).

``send_u_recv(x, src, dst)`` = gather ``x[src]``, reduce onto ``dst`` rows;
``send_ue_recv`` fuses an edge-feature op into the message;
``send_uv`` emits the per-edge message. All three are jit-safe: the default
output row count is ``x.shape[0]`` (static), matching the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import primitive, unwrap
from .math import seg_reduce

_MSG_OPS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def _rows(x, out_size):
    if out_size is None:
        return None
    n = int(unwrap(out_size))
    return n if n > 0 else None


@primitive
def _send_u_recv(x, src_index, dst_index, reduce_op="sum", rows=None):
    msg = jnp.take(x, src_index.astype(jnp.int32), axis=0)
    return seg_reduce(msg, dst_index, rows or x.shape[0], reduce_op)


@primitive
def _send_ue_recv(x, y, src_index, dst_index, message_op="add",
                  reduce_op="sum", rows=None):
    msg = _MSG_OPS[message_op](
        jnp.take(x, src_index.astype(jnp.int32), axis=0), y)
    return seg_reduce(msg, dst_index, rows or x.shape[0], reduce_op)


@primitive
def _send_uv(x, y, src_index, dst_index, message_op="add"):
    return _MSG_OPS[message_op](
        jnp.take(x, src_index.astype(jnp.int32), axis=0),
        jnp.take(y, dst_index.astype(jnp.int32), axis=0))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    return _send_u_recv(x, src_index, dst_index, reduce_op=reduce_op,
                        rows=_rows(x, out_size))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    return _send_ue_recv(x, y, src_index, dst_index, message_op=message_op,
                         reduce_op=reduce_op, rows=_rows(x, out_size))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    return _send_uv(x, y, src_index, dst_index, message_op=message_op)

"""Install-tree introspection (reference ``python/paddle/sysconfig.py``:20,39).

Points at this package's native runtime artifacts (the C++ IO runtime and
custom-op toolchain build outputs live under ``paddle_tpu/lib``/``include``).
"""
import os

__all__ = ['get_include', 'get_lib']


def get_include():
    """Directory containing the C headers for building custom ops against
    the framework (created on demand by the custom-op builder)."""
    return os.path.join(os.path.dirname(__file__), 'include')


def get_lib():
    """Directory containing the framework's native shared libraries
    (e.g. ``libpaddle_tpu_io.so``, the C++ data-loader runtime)."""
    libs = os.path.join(os.path.dirname(__file__), 'lib')
    native = os.path.join(os.path.dirname(__file__), 'io', 'native')
    return libs if os.path.isdir(libs) else native

"""Distributed checkpoint: per-shard files + manifest, reshard on load.

Capability analog of ``python/paddle/distributed/checkpoint/
save_state_dict.py:104`` / ``load_state_dict.py:377`` (SURVEY D23). Like
the reference, a checkpoint directory holds one data file per process
(``{rank}_0.distcp.npz``) containing only that process's *unique* shards
(replicas deduped by ``replica_id == 0``, the reference's ``dedup_tensor``),
plus a ``metadata`` manifest mapping every (tensor, global_offset) shard to
its file.

Loading reassembles exactly the shards overlapping each destination
tensor and places the result onto the destination's *current* sharding
(``device_put`` — XLA moves the bytes), so a checkpoint saved on one
mesh topology restores onto any other: the reference's cross-topology
reshard engine (``get_read_items``/``compute_overlap``) collapses into
shard-gather + device_put under the single-controller model.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

_MANIFEST = "metadata"


def _manifest_file(rank: int) -> str:
    return _MANIFEST if rank == 0 else f"{_MANIFEST}.{rank}"


def _data_file(rank: int) -> str:
    return f"{rank}_0.distcp.npz"


def _shard_key(key: str, offset) -> str:
    return key + "|" + ",".join(str(int(o)) for o in offset)


def _offsets_of(index, shape):
    """Global offset tuple from a jax shard ``index`` (tuple of slices)."""
    if index is None:
        return (0,) * len(shape)
    return tuple((s.start or 0) for s in index)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``save_state_dict.py:104``: write this process's unique
    shards + the manifest. Works for replicated, fully-sharded, and
    hybrid placements."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = Metadata()
    arrays = {}

    for key, v in state_dict.items():
        val = v._read() if isinstance(v, Tensor) else v
        if isinstance(val, jax.Array) and len(val.sharding.device_set) > 1:
            shards = [s for s in val.addressable_shards
                      if s.replica_id == 0]  # dedup replicas
            gshape = tuple(val.shape)
            seen = set()
            for s in shards:
                off = _offsets_of(s.index, gshape)
                if off in seen:  # same block from another device
                    continue
                seen.add(off)
                block = np.asarray(s.data)
                arrays[_shard_key(key, off)] = block
                lm = LocalTensorMetadata(off, tuple(block.shape),
                                         str(block.dtype))
                meta.state_dict_metadata.setdefault(key, []).append(lm)
                meta.storage_metadata[LocalTensorIndex(key, off)] = \
                    _data_file(rank)
            meta.global_shapes[key] = gshape
        else:
            block = np.asarray(val)
            off = (0,) * block.ndim
            arrays[_shard_key(key, off)] = block
            meta.state_dict_metadata[key] = [
                LocalTensorMetadata(off, tuple(block.shape),
                                    str(block.dtype))]
            meta.storage_metadata[LocalTensorIndex(key, off)] = \
                _data_file(rank)
            meta.global_shapes[key] = tuple(block.shape)

    np.savez(os.path.join(path, _data_file(rank)), **arrays)
    # every process writes its own manifest piece — addressable_shards is
    # per-process, so on a multi-host pod no single rank sees every shard;
    # load merges all pieces (the reference's merge_state_dict_metadata)
    with open(os.path.join(path, _manifest_file(rank)), "wb") as f:
        pickle.dump(meta, f)


def _read_manifest(path) -> Metadata:
    """Merge every rank's manifest piece (reference
    ``save_state_dict.py:50`` merge_state_dict_metadata)."""
    pieces = sorted(f for f in os.listdir(path)
                    if f == _MANIFEST or f.startswith(_MANIFEST + "."))
    if not pieces:
        raise FileNotFoundError(f"no checkpoint manifest under {path}")
    merged = Metadata()
    for fname in pieces:
        with open(os.path.join(path, fname), "rb") as f:
            meta = pickle.load(f)
        for key, lms in meta.state_dict_metadata.items():
            have = merged.state_dict_metadata.setdefault(key, [])
            seen = {lm.global_offset for lm in have}
            have.extend(lm for lm in lms if lm.global_offset not in seen)
        for idx, fn in meta.storage_metadata.items():
            merged.storage_metadata.setdefault(idx, fn)
        merged.global_shapes.update(meta.global_shapes)
    return merged


def _load_file(path, fname, cache):
    if fname not in cache:
        fp = os.path.join(path, fname)
        if not os.path.exists(fp):
            raise FileNotFoundError(
                f"checkpoint shard file {fp} missing (saved from more "
                "processes than are loading? copy all shard files)")
        cache[fname] = np.load(fp)
    return cache[fname]


def _assemble(meta: Metadata, path, key, cache):
    """Gather every shard of ``key`` into the global ndarray."""
    if key not in meta.state_dict_metadata:
        raise KeyError(f"checkpoint has no tensor '{key}'")
    gshape = meta.global_shapes[key]
    shards = meta.state_dict_metadata[key]
    if len(shards) == 1 and tuple(shards[0].local_shape) == tuple(gshape):
        fname = meta.storage_metadata[
            LocalTensorIndex(key, shards[0].global_offset)]
        return _load_file(path, fname, cache)[
            _shard_key(key, shards[0].global_offset)]
    out = np.empty(gshape, dtype=shards[0].dtype)
    for lm in shards:
        fname = meta.storage_metadata[
            LocalTensorIndex(key, lm.global_offset)]
        block = _load_file(path, fname, cache)[
            _shard_key(key, lm.global_offset)]
        sl = tuple(slice(o, o + s)
                   for o, s in zip(lm.global_offset, lm.local_shape))
        out[sl] = block
    return out


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``load_state_dict.py:377``: fill ``state_dict``'s tensors
    in place, resharding each value onto the tensor's *current* placement
    (cross-topology restore). Keys in the checkpoint but not requested are
    ignored (partial load, as the reference)."""
    meta = _read_manifest(path)
    cache = {}
    for key, t in state_dict.items():
        arr = _assemble(meta, path, key, cache)
        if isinstance(t, Tensor):
            cur = t._read()
            if not isinstance(cur, jax.core.Tracer):
                arr = arr.astype(cur.dtype)
                sharding = getattr(cur, "sharding", None)
                val = (jax.device_put(arr, sharding)
                       if sharding is not None else arr)
                t._write(val)
            else:
                t._write(arr)
        else:
            state_dict[key] = arr
    return state_dict


def get_checkpoint_files(path):
    """Reference ``load_state_dict.py:43``: (metadata files, data files)."""
    files = os.listdir(path)
    return (sorted(f for f in files
                   if f == _MANIFEST or f.startswith(_MANIFEST + ".")),
            sorted(f for f in files if f.endswith(".distcp.npz")))

"""Distributed checkpoint: per-shard files + manifest, reshard on load.

Capability analog of ``python/paddle/distributed/checkpoint/
save_state_dict.py:104`` / ``load_state_dict.py:377`` (SURVEY D23). Like
the reference, a checkpoint directory holds one data file per process
(``{rank}_0.distcp.npz``) containing only that process's *unique* shards
(replicas deduped by ``replica_id == 0``, the reference's ``dedup_tensor``),
plus a ``metadata`` manifest mapping every (tensor, global_offset) shard to
its file.

Loading reassembles exactly the shards overlapping each destination
tensor and places the result onto the destination's *current* sharding
(``device_put`` — XLA moves the bytes), so a checkpoint saved on one
mesh topology restores onto any other: the reference's cross-topology
reshard engine (``get_read_items``/``compute_overlap``) collapses into
shard-gather + device_put under the single-controller model.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from ...core.errors import (CheckpointCorruptError,
                            CheckpointNotFoundError, NotFoundError)
from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

_MANIFEST = "metadata"


def _manifest_file(rank: int) -> str:
    return _MANIFEST if rank == 0 else f"{_MANIFEST}.{rank}"


def _data_file(rank: int) -> str:
    return f"{rank}_0.distcp.npz"


def _shard_key(key: str, offset) -> str:
    return key + "|" + ",".join(str(int(o)) for o in offset)


def _offsets_of(index, shape):
    """Global offset tuple from a jax shard ``index`` (tuple of slices)."""
    if index is None:
        return (0,) * len(shape)
    return tuple((s.start or 0) for s in index)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``save_state_dict.py:104``: write this process's unique
    shards + the manifest. Works for replicated, fully-sharded, and
    hybrid placements."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = Metadata()
    arrays = {}

    for key, v in state_dict.items():
        val = v._read() if isinstance(v, Tensor) else v
        if isinstance(val, jax.Array) and len(val.sharding.device_set) > 1:
            shards = [s for s in val.addressable_shards
                      if s.replica_id == 0]  # dedup replicas
            gshape = tuple(val.shape)
            seen = set()
            for s in shards:
                off = _offsets_of(s.index, gshape)
                if off in seen:  # same block from another device
                    continue
                seen.add(off)
                block = np.asarray(s.data)
                arrays[_shard_key(key, off)] = block
                lm = LocalTensorMetadata(off, tuple(block.shape),
                                         str(block.dtype))
                meta.state_dict_metadata.setdefault(key, []).append(lm)
                meta.storage_metadata[LocalTensorIndex(key, off)] = \
                    _data_file(rank)
            meta.global_shapes[key] = gshape
        else:
            block = np.asarray(val)
            off = (0,) * block.ndim
            arrays[_shard_key(key, off)] = block
            meta.state_dict_metadata[key] = [
                LocalTensorMetadata(off, tuple(block.shape),
                                    str(block.dtype))]
            meta.storage_metadata[LocalTensorIndex(key, off)] = \
                _data_file(rank)
            meta.global_shapes[key] = tuple(block.shape)

    # atomic commits (resilience.atomic): a death mid-save leaves stray
    # temp files, never a half-written .npz/manifest under the real name.
    # The manifest lands LAST — a checkpoint with data but no manifest
    # reads as absent, not corrupt.
    from ...resilience.atomic import atomic_write

    with atomic_write(os.path.join(path, _data_file(rank))) as f:
        np.savez(f, **arrays)
    # every process writes its own manifest piece — addressable_shards is
    # per-process, so on a multi-host pod no single rank sees every shard;
    # load merges all pieces (the reference's merge_state_dict_metadata)
    with atomic_write(os.path.join(path, _manifest_file(rank))) as f:
        pickle.dump(meta, f)


def _read_manifest(path) -> Metadata:
    """Merge every rank's manifest piece (reference
    ``save_state_dict.py:50`` merge_state_dict_metadata)."""
    try:
        entries = os.listdir(path)
    except FileNotFoundError:
        raise CheckpointNotFoundError(
            f"no checkpoint directory at {path} "
            f"[{CheckpointNotFoundError.error_code}]") from None
    pieces = sorted(f for f in entries
                    if f == _MANIFEST or f.startswith(_MANIFEST + "."))
    if not pieces:
        raise CheckpointNotFoundError(
            f"no checkpoint manifest under {path} (torn save? a "
            "complete checkpoint always has one) "
            f"[{CheckpointNotFoundError.error_code}]")
    merged = Metadata()
    for fname in pieces:
        try:
            with open(os.path.join(path, fname), "rb") as f:
                meta = pickle.load(f)
        except Exception as e:
            raise CheckpointCorruptError(
                f"checkpoint manifest {os.path.join(path, fname)} is "
                f"unreadable ({type(e).__name__}: {e}) — torn write? "
                f"[{CheckpointCorruptError.error_code}]") from e
        for key, lms in meta.state_dict_metadata.items():
            have = merged.state_dict_metadata.setdefault(key, [])
            seen = {lm.global_offset for lm in have}
            have.extend(lm for lm in lms if lm.global_offset not in seen)
        for idx, fn in meta.storage_metadata.items():
            merged.storage_metadata.setdefault(idx, fn)
        merged.global_shapes.update(meta.global_shapes)
    return merged


def _load_file(path, fname, cache):
    if fname not in cache:
        fp = os.path.join(path, fname)
        if not os.path.exists(fp):
            raise CheckpointCorruptError(
                f"checkpoint shard file {fp} missing (torn save, or "
                "saved from more processes than are loading? copy all "
                f"shard files) [{CheckpointCorruptError.error_code}]")
        try:
            cache[fname] = np.load(fp)
        except Exception as e:
            raise CheckpointCorruptError(
                f"checkpoint shard file {fp} is unreadable "
                f"({type(e).__name__}: {e}) — torn write? "
                f"[{CheckpointCorruptError.error_code}]") from e
    return cache[fname]


def _shard_block(meta, path, key, lm, cache):
    """One shard's block, with manifest-vs-file mismatches coded."""
    fname = meta.storage_metadata[LocalTensorIndex(key, lm.global_offset)]
    data = _load_file(path, fname, cache)
    skey = _shard_key(key, lm.global_offset)
    if skey not in data.files:
        raise CheckpointCorruptError(
            f"manifest mismatch: {fname} has no entry {skey!r} for "
            f"tensor '{key}' (manifest and data file disagree) "
            f"[{CheckpointCorruptError.error_code}]")
    return data[skey]


def _assemble(meta: Metadata, path, key, cache):
    """Gather every shard of ``key`` into the global ndarray."""
    if key not in meta.state_dict_metadata:
        raise NotFoundError(
            f"checkpoint has no tensor '{key}' "
            f"[{NotFoundError.error_code}]")
    gshape = meta.global_shapes[key]
    shards = meta.state_dict_metadata[key]
    if len(shards) == 1 and tuple(shards[0].local_shape) == tuple(gshape):
        return _shard_block(meta, path, key, shards[0], cache)
    out = np.empty(gshape, dtype=shards[0].dtype)
    for lm in shards:
        block = _shard_block(meta, path, key, lm, cache)
        sl = tuple(slice(o, o + s)
                   for o, s in zip(lm.global_offset, lm.local_shape))
        out[sl] = block
    return out


def validate_checkpoint(path, keys=None):
    """Validate the manifest and the presence of every shard file it
    references (all keys, or just ``keys``). Returns the merged
    manifest; raises ``CheckpointNotFoundError`` /
    ``CheckpointCorruptError`` (listing EVERY offending key/file, not
    just the first) on failure."""
    meta = _read_manifest(path)
    want = list(meta.state_dict_metadata) if keys is None else list(keys)
    missing_keys = [k for k in want if k not in meta.state_dict_metadata]
    if missing_keys:
        raise NotFoundError(
            f"checkpoint at {path} has no tensor(s) {missing_keys} "
            f"(it holds {len(meta.state_dict_metadata)} tensors) "
            f"[{NotFoundError.error_code}]")
    # shard COVERAGE: a rank that died between its data write and its
    # manifest write leaves a merged manifest that lists only the other
    # ranks' shards — every file it names exists, but _assemble would
    # fill the dead rank's regions of np.empty with garbage. Disjoint
    # shards covering the global shape have volumes summing to it.
    uncovered = []
    for key in want:
        gshape = meta.global_shapes.get(key)
        vol = sum(int(np.prod(lm.local_shape))
                  for lm in meta.state_dict_metadata[key])
        if gshape is None or vol != int(np.prod(gshape)):
            uncovered.append(key)
    if uncovered:
        raise CheckpointCorruptError(
            f"checkpoint at {path}: shards of {uncovered} do not cover "
            "their global shapes (a rank's manifest piece missing? "
            "torn multi-host save — copy every rank's manifest) "
            f"[{CheckpointCorruptError.error_code}]")
    bad = {}  # file -> affected keys
    for key in want:
        for lm in meta.state_dict_metadata[key]:
            idx = LocalTensorIndex(key, lm.global_offset)
            fname = meta.storage_metadata.get(idx)
            if fname is None:
                bad.setdefault("<no storage entry>", set()).add(key)
            elif not os.path.exists(os.path.join(path, fname)):
                bad.setdefault(fname, set()).add(key)
    if bad:
        detail = "; ".join(
            f"{f} (tensors: {sorted(ks)})" for f, ks in sorted(bad.items()))
        raise CheckpointCorruptError(
            f"checkpoint at {path} is missing shard data: {detail} "
            f"[{CheckpointCorruptError.error_code}]")
    return meta


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``load_state_dict.py:377``: fill ``state_dict``'s tensors
    in place, resharding each value onto the tensor's *current* placement
    (cross-topology restore). Keys in the checkpoint but not requested are
    ignored (partial load, as the reference). Validation runs up front:
    missing tensors / shard files raise coded errors (PDT-E002 /
    PDT-E014) listing every offender before anything is written."""
    meta = validate_checkpoint(path, keys=state_dict.keys())
    cache = {}
    for key, t in state_dict.items():
        arr = _assemble(meta, path, key, cache)
        if isinstance(t, Tensor):
            cur = t._read()
            if not isinstance(cur, jax.core.Tracer):
                arr = arr.astype(cur.dtype)
                sharding = getattr(cur, "sharding", None)
                val = (jax.device_put(arr, sharding)
                       if sharding is not None else arr)
                t._write(val)
            else:
                t._write(arr)
        else:
            state_dict[key] = arr
    return state_dict


def get_checkpoint_files(path):
    """Reference ``load_state_dict.py:43``: (metadata files, data files)."""
    files = os.listdir(path)
    return (sorted(f for f in files
                   if f == _MANIFEST or f.startswith(_MANIFEST + ".")),
            sorted(f for f in files if f.endswith(".distcp.npz")))

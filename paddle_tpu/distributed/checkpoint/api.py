"""Distributed checkpoint with cross-topology reshard on load.

Capability analog of ``python/paddle/distributed/checkpoint/
save_state_dict.py:104`` / ``load_state_dict.py:377`` (SURVEY D23). The
reference writes one shard-file per rank plus a metadata manifest and
reassembles/reshards on load. Single-controller TPU: the controller sees
the global value of every dist tensor, so the checkpoint holds global
arrays plus each tensor's sharding metadata; loading into a *different*
mesh topology is a ``device_put`` onto the new sharding — XLA moves the
bytes (the reference's cross-topology reshard engine collapses into that).

For multi-host pods the same layout works per-process via
``jax.experimental.multihost_utils`` gather; orbax-style per-shard zarr is
a future optimization, not a semantic change.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

_META = "meta.pkl"
_DATA = "data.npz"


def _spec_to_meta(dist):
    if dist is None:
        return None
    mesh, spec = dist
    from ..auto_parallel.api import _to_partition_spec
    if hasattr(mesh, "jmesh"):  # ProcessMesh
        names = list(mesh.dim_names)
        shape = list(mesh.shape)
    else:  # raw jax Mesh
        names = list(mesh.axis_names)
        shape = [mesh.shape[n] for n in names]
    if not isinstance(spec, P) and isinstance(spec, (list, tuple)):
        spec = _to_partition_spec(mesh, spec)
    entries = []
    if isinstance(spec, P):
        for e in spec:
            if e is None:
                entries.append(None)
            elif isinstance(e, tuple):
                entries.append(list(e))
            else:
                entries.append([e])
    return {"axis_names": names, "mesh_shape": shape, "spec": entries}


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``save_state_dict.py:104``."""
    os.makedirs(path, exist_ok=True)
    arrays, meta = {}, {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            val = v._read()
            arrays[k] = np.asarray(val)
            meta[k] = _spec_to_meta(v._dist)
        else:
            arrays[k] = np.asarray(v)
            meta[k] = None
    np.savez(os.path.join(path, _DATA), **arrays)
    with open(os.path.join(path, _META), "wb") as f:
        pickle.dump(meta, f)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, **kwargs):
    """Reference ``load_state_dict.py:377``: fills ``state_dict``'s tensors
    in place, resharding each value onto the tensor's *current* placement
    (cross-topology restore). Tensors in the checkpoint but not in
    ``state_dict`` are ignored, matching the reference's partial-load."""
    data = np.load(os.path.join(path, _DATA))
    for k, t in state_dict.items():
        if k not in data.files:
            raise KeyError(f"checkpoint {path} has no tensor '{k}'")
        arr = data[k]
        if isinstance(t, Tensor):
            cur = t._read()
            if not isinstance(cur, jax.core.Tracer):
                # keep the destination topology's sharding
                sharding = getattr(cur, "sharding", None)
                val = jax.device_put(arr.astype(cur.dtype), sharding) \
                    if sharding is not None else arr.astype(cur.dtype)
                t._write(val)
            else:
                t._write(arr)
        else:
            state_dict[k] = arr
    return state_dict

"""Checkpoint manifest types (reference
``python/paddle/distributed/checkpoint/metadata.py``:20,30,40).

A checkpoint directory holds N shard data files plus one ``metadata``
manifest. The manifest records, per tensor key, where every local shard
sits in the global tensor (``LocalTensorMetadata``) and which file stores
it (``storage_metadata``, keyed by ``LocalTensorIndex``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class LocalTensorMetadata:
    """Placement of one local shard inside its global tensor."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str = "float32"


@dataclass(frozen=True)
class LocalTensorIndex:
    """Identity of one local shard (tensor key + offset)."""
    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    # tensor key -> every shard's placement
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    # shard identity -> data file holding it
    storage_metadata: Dict[LocalTensorIndex, str] = field(
        default_factory=dict)
    # tensor key -> global shape (reassembly target)
    global_shapes: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    flat_mapping: Dict[str, Tuple[str, ...]] = field(default_factory=dict)

from .api import (  # noqa: F401
    save_state_dict, load_state_dict, get_checkpoint_files)
from .metadata import (  # noqa: F401
    LocalTensorIndex, LocalTensorMetadata, Metadata)
from . import api  # noqa: F401
